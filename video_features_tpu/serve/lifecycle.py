"""Request lifecycle: the manifest-backed record every serve request gets.

A batch run's unit of record is the video (runtime/faults.py manifest);
the daemon's unit of record is the *request* — same video, different
identity: two users asking for the same clip are two requests, and each
one must end in a queryable terminal state. States:

    queued -> dispatched -> done | failed
    queued -> rejected                (backpressure / bad input / breaker)
    queued -> expired                 (deadline passed before dispatch)
    queued | dispatched -> cancelled  (DELETE /v1/requests/<id>, .cancel)

Every transition is appended to a :class:`~video_features_tpu.runtime.
faults.RunManifest` rooted at ``<output>/_requests`` (so the extraction
manifest under ``<output>/_manifest`` stays purely per-video), and the
terminal state is additionally written as ``<output>/_requests/<id>.json``
— the durable per-request result record the status endpoint serves after
the in-memory map forgets (daemon restart). Failure records reuse the
``classify_error`` taxonomy from runtime/faults.py, so a request that
died of a transient decode flake reads exactly like the batch manifest
would read it.

No jax imports; everything here runs on source/HTTP threads.
"""

from __future__ import annotations

import dataclasses
import glob
import json
import os
import re
import threading
import time
import uuid
from typing import Any, Dict, List, Optional, Tuple

from video_features_tpu.io.sink import atomic_write_json
from video_features_tpu.runtime import faults as faults_mod
from video_features_tpu.runtime.faults import RunManifest

REQUESTS_DIRNAME = "_requests"

# queued/dispatched are transitional; done/failed/rejected/expired/
# cancelled are terminal (merge_manifest treats all five as terminal
# when folding the request manifest, so a restart never resurrects a
# rejected/expired/cancelled request as live). 'deferred' and 'requeued'
# are manifest-only notes: the request left THIS process but its spool
# file is the durable copy that re-submits it.
REQUEST_STATES = (
    "queued", "dispatched", "done", "failed", "rejected", "expired", "cancelled",
)
TERMINAL_STATES = ("done", "failed", "rejected", "expired", "cancelled")

# non-terminal manifest statuses that need NO reconciliation after a
# crash: the spool file still exists and re-submits the request itself
_SPOOL_SAFE_STATES = ("deferred", "requeued")

# request ids become result filenames: constrain them so a hostile id
# can never traverse out of _requests/ (the HTTP source accepts ids)
_ID_RE = re.compile(r"^[A-Za-z0-9][A-Za-z0-9._-]{0,99}$")

# the admission key's catch-all bucket for requests that do not declare
# one: they still coalesce with each other (the extractor's own agg_key
# keeps truly mixed shapes out of one fused dispatch)
DEFAULT_BUCKET = "~"


class BadRequest(ValueError):
    """Malformed request payload (unknown feature type, missing path,
    unsafe id). Permanent by nature: re-sending the same bytes fails
    the same way."""


class DuplicateRequest(BadRequest):
    """A request id that is already tracked live in THIS process. Still
    a 400 for HTTP callers (it subclasses :class:`BadRequest`), but the
    spool source treats it as benign — after a lease steal or a
    reconcile requeue the same request can briefly exist as two spool
    files, and the loser must be dropped, not quarantined."""


class InvalidMedia(BadRequest):
    """The request was well-formed but its media failed the preflight
    probe (io/probe.py): HTTP callers get 422 ``invalid_media`` with the
    probe's reason, spool files quarantine via ``.bad``+``.why``, and —
    unlike a plain BadRequest — the request had an identity, so a
    durable ``rejected`` record is written before this is raised.
    Permanent, input-classified: never a breaker tick, never a retry."""

    def __init__(self, reason: str, record: Optional[Dict[str, Any]] = None):
        super().__init__(reason)
        self.reason = reason
        self.record = record or {}


@dataclasses.dataclass
class ExtractionRequest:
    """One admitted unit of work. ``bucket`` is the client's spatial-
    bucket hint — the coalescing half of the admission key; the fused
    dispatch itself is still guarded by the extractor's ``agg_key``, so
    a wrong hint costs batching efficiency, never correctness."""

    feature_type: str
    video_path: str
    id: str = dataclasses.field(default_factory=lambda: uuid.uuid4().hex[:12])
    bucket: str = DEFAULT_BUCKET
    source: str = "local"  # http | spool | warmup | local
    received_ts: float = dataclasses.field(default_factory=time.time)
    # scheduling hints (ISSUE 8): tier 0..9 (higher = more urgent) and a
    # latency budget in ms from admission; the batcher stamps the
    # absolute admitted_at/deadline_at on ITS clock at admit time, so
    # the fake-clock tests and the EDF ranks share one time base
    priority: int = 0
    deadline_ms: Optional[float] = None
    admitted_at: Optional[float] = None
    deadline_at: Optional[float] = None

    def key(self) -> Tuple[str, str]:
        """The admission-control key: same-(feature_type, bucket)
        requests may coalesce into one fused --video_batch group."""
        return (self.feature_type, self.bucket)


def parse_request(payload: Dict[str, Any], source: str) -> ExtractionRequest:
    """Validate one request dict (HTTP body or spool file) into an
    :class:`ExtractionRequest`; raises :class:`BadRequest` naming the
    problem (the sources turn that into 400 / a rejected record)."""
    if not isinstance(payload, dict):
        raise BadRequest(f"request body must be a JSON object, got {type(payload).__name__}")
    ft = payload.get("feature_type")
    if not ft or not isinstance(ft, str):
        raise BadRequest("missing 'feature_type'")
    video = payload.get("video_path")
    if not video or not isinstance(video, str):
        raise BadRequest("missing 'video_path'")
    kw: Dict[str, Any] = {"feature_type": ft, "video_path": video, "source": source}
    rid = payload.get("id")
    if rid is not None:
        if not isinstance(rid, str) or not _ID_RE.match(rid):
            raise BadRequest(
                "bad 'id': need 1-100 chars of [A-Za-z0-9._-] starting alphanumeric"
            )
        kw["id"] = rid
    bucket = payload.get("bucket")
    if bucket is not None:
        if not isinstance(bucket, str) or len(bucket) > 32:
            raise BadRequest("bad 'bucket': expected a short string like '640x480'")
        kw["bucket"] = bucket
    priority = payload.get("priority")
    if priority is not None:
        if isinstance(priority, bool) or not isinstance(priority, int) \
                or not 0 <= priority <= 9:
            raise BadRequest(
                "bad 'priority': expected an integer 0..9 (higher = more urgent)"
            )
        kw["priority"] = priority
    deadline_ms = payload.get("deadline_ms")
    if deadline_ms is not None:
        if isinstance(deadline_ms, bool) or not isinstance(deadline_ms, (int, float)) \
                or not 0 < float(deadline_ms) <= 7 * 24 * 3600 * 1000:
            raise BadRequest(
                "bad 'deadline_ms': expected a positive number of milliseconds "
                "(latency budget from admission)"
            )
        kw["deadline_ms"] = float(deadline_ms)
    return ExtractionRequest(**kw)


def requests_root(output_root: str) -> str:
    return os.path.join(output_root, REQUESTS_DIRNAME)


REPLICAS_DIRNAME = "_replicas"


class ReplicaRegistry:
    """Fleet membership over the shared output store (ISSUE 18): each
    serve replica periodically touches ``_requests/_replicas/<id>.json``;
    liveness is heartbeat-file mtime, on the WALL clock — the one clock
    N processes on a shared filesystem actually share. Survivors use
    :meth:`live` to decide which dead replicas' in-flight requests to
    reclaim (``RequestTracker.reconcile``) and which spool leases are
    stale (``SpoolWatcher``). Tests fake staleness with ``os.utime``."""

    def __init__(self, output_root: str, replica_id: str) -> None:
        self.dir = os.path.join(requests_root(output_root), REPLICAS_DIRNAME)
        self.replica_id = str(replica_id)
        self.path = os.path.join(self.dir, f"{self.replica_id}.json")

    def beat(self) -> None:
        """Refresh this replica's heartbeat (tmp + rename: a reader never
        sees a torn file, and the rename refreshes mtime atomically)."""
        try:
            atomic_write_json(
                self.path,
                {"replica": self.replica_id, "pid": os.getpid(),
                 "ts": round(time.time(), 3)},
            )
        except OSError:
            pass  # a missed beat is survivable; a crashed beat is not

    def retire(self) -> None:
        """Clean shutdown: drop the heartbeat so survivors reclaim this
        replica's leases immediately instead of after a timeout."""
        try:
            os.unlink(self.path)
        except OSError:
            pass

    def ages(self, now: Optional[float] = None) -> Dict[str, float]:
        """``{replica_id: heartbeat age in seconds}`` for every replica
        with a heartbeat file (including this one)."""
        now = time.time() if now is None else now
        out: Dict[str, float] = {}
        try:
            names = os.listdir(self.dir)
        except OSError:
            return out
        for name in names:
            if not name.endswith(".json"):
                continue
            try:
                mtime = os.stat(os.path.join(self.dir, name)).st_mtime
            except OSError:
                continue
            out[name[: -len(".json")]] = max(now - mtime, 0.0)
        return out

    def live(self, timeout_s: float, now: Optional[float] = None) -> set:
        """Replica ids whose heartbeat is fresher than ``timeout_s``.
        ``timeout_s <= 0`` means liveness is never inferred: everyone
        with a heartbeat file counts as live (steal protocol disabled)."""
        ages = self.ages(now)
        if timeout_s <= 0:
            return set(ages)
        return {rid for rid, age in ages.items() if age <= timeout_s}


class RequestTracker:
    """Thread-safe request registry + the manifest/result-file writers.

    Sources admit from their own threads, the batcher's dispatcher
    transitions from its thread, and the status endpoint reads from HTTP
    handler threads — one lock covers the in-memory map; the manifest
    has its own (runtime/faults.py)."""

    def __init__(
        self,
        output_root: str,
        telemetry: Any = None,
        slo: Any = None,
        clock: Any = time.monotonic,
        replica_id: Optional[str] = None,
    ) -> None:
        self.output_root = output_root
        self.results_dir = requests_root(output_root)
        self.manifest = RunManifest(self.results_dir)
        # fleet attribution (ISSUE 18): every manifest line this tracker
        # writes carries replica=<id>, so a survivor's reconcile can tell
        # a DEAD replica's in-flight requests from a live peer's
        self.replica_id = replica_id
        self.telemetry = telemetry
        # the daemon's SloTracker (runtime/telemetry.py) and its
        # scheduling clock: latency/queue-wait samples are measured on
        # the same (injectable) clock the batcher stamps admitted_at/
        # deadline_at with, so fake-clock tests and EDF ranks agree
        self.slo = slo
        self._clock = clock
        self._lock = threading.Lock()
        self._records: Dict[str, Dict[str, Any]] = {}
        self._spans: Dict[str, Any] = {}  # request id -> open telemetry token
        self._qspans: Dict[str, Any] = {}  # request id -> open queue_wait token

    # -- transitions ----------------------------------------------------

    def admit(self, req: ExtractionRequest) -> Dict[str, Any]:
        rec = {
            "id": req.id,
            "state": "queued",
            "feature_type": req.feature_type,
            "video_path": req.video_path,
            "bucket": req.bucket,
            "source": req.source,
            "received_ts": round(req.received_ts, 4),
        }
        if req.priority:
            rec["priority"] = int(req.priority)
        if req.deadline_ms is not None:
            rec["deadline_ms"] = float(req.deadline_ms)
        with self._lock:
            if req.id in self._records:
                raise DuplicateRequest(f"duplicate request id {req.id!r}")
            self._records[req.id] = rec
        self._count("requests_admitted")
        if self.telemetry is not None and self.telemetry.enabled:
            token = self.telemetry.begin(
                "request", video=req.video_path, request=req.id,
                feature_type=req.feature_type, bucket=req.bucket,
            )
            if token is not None:
                # the queue_wait child measures admission -> group
                # dispatch (closed in dispatched(), or at the terminal
                # transition for requests that never dispatch); explicit
                # parent= pins it under the request span regardless of
                # what is on the opener thread's span stack
                qtoken = self.telemetry.begin(
                    "queue_wait", video=req.video_path, request=req.id,
                    feature_type=req.feature_type, bucket=req.bucket,
                    parent=token.span_id,
                )
                with self._lock:
                    self._spans[req.id] = token
                    if qtoken is not None:
                        self._qspans[req.id] = qtoken
        # the queued record carries the full resubmittable payload: it
        # is what reconcile() rebuilds a request from after a crash
        extra: Dict[str, Any] = {}
        if req.priority:
            extra["priority"] = int(req.priority)
        if req.deadline_ms is not None:
            extra["deadline_ms"] = float(req.deadline_ms)
        self._record(
            f"request:{req.id}", "queued",
            feature_type=req.feature_type, video_path=req.video_path,
            bucket=req.bucket, source=req.source, **extra,
        )
        return dict(rec)

    def dispatched(self, req: ExtractionRequest, group_size: int) -> None:
        queue_wait = None
        if req.admitted_at is not None:
            queue_wait = max(self._clock() - req.admitted_at, 0.0)
        with self._lock:
            rec = self._records.get(req.id)
            if rec is not None:
                rec["state"] = "dispatched"
                rec["group_size"] = int(group_size)
                if queue_wait is not None:
                    rec["queue_wait_s"] = round(queue_wait, 4)
            qtoken = self._qspans.pop(req.id, None)
        if qtoken is not None:
            qtoken.finish(group_size=int(group_size))
        self._record(
            f"request:{req.id}", "dispatched", group_size=int(group_size)
        )

    def finish(
        self,
        req: ExtractionRequest,
        status: str,
        error_class: Optional[str] = None,
        error_type: Optional[str] = None,
        message: Optional[str] = None,
        features: Optional[List[str]] = None,
    ) -> Dict[str, Any]:
        """Terminal transition (done/failed/rejected): update the map,
        append the manifest record, write the durable result JSON,
        close the request telemetry span, and fold the SLO sample
        (latency, queue wait, deadline miss) into the daemon's
        rolling-window tracker."""
        assert status in TERMINAL_STATES, status
        now_mono = self._clock()
        # a deadline is missed when the request was supposed to finish
        # (ran or expired) and its budget had passed by the terminal
        # transition; cancellations/rejections are not missed promises
        missed = status == "expired" or (
            status in ("done", "failed")
            and req.deadline_at is not None
            and now_mono > req.deadline_at
        )
        with self._lock:
            rec = self._records.get(req.id)
            if rec is None:
                rec = {"id": req.id, "video_path": req.video_path,
                       "feature_type": req.feature_type, "bucket": req.bucket}
                self._records[req.id] = rec
            rec["state"] = status
            rec["finished_ts"] = round(time.time(), 4)
            rec["wall_s"] = round(rec["finished_ts"] - rec.get("received_ts", rec["finished_ts"]), 4)
            if missed:
                rec["deadline_missed"] = True
            if error_class is not None:
                rec["error_class"] = error_class
            if error_type is not None:
                rec["error_type"] = error_type
            if message is not None:
                rec["message"] = str(message)[:500]
            if features is not None:
                rec["features"] = list(features)
            out = dict(rec)
            token = self._spans.pop(req.id, None)
            qtoken = self._qspans.pop(req.id, None)
        if qtoken is not None:
            # never dispatched (expired/cancelled/rejected while queued):
            # the queue_wait interval ends at the terminal transition
            qtoken.finish(state=status)
        if token is not None:
            token.finish(state=status)
        self._count(f"requests_{status}")
        if missed:
            self._count("deadline_missed")
        if self.slo is not None:
            latency = (
                now_mono - req.admitted_at if req.admitted_at is not None
                else out["wall_s"]
            )
            self.slo.record(
                status,
                latency_s=max(float(latency), 0.0),
                queue_wait_s=out.get("queue_wait_s"),
                priority=int(req.priority or 0),
                deadline_missed=missed,
            )
        extra = {
            k: out[k]
            for k in ("error_class", "error_type", "message", "wall_s")
            if k in out
        }
        self._record(f"request:{req.id}", status, **extra)
        try:
            self._write_result(out)
        except OSError as exc:
            # degraded durability, not a lost outcome: the manifest line
            # above already landed, the in-memory record still answers
            # queries, and the event makes the gap auditable
            self.manifest.event(
                "result_write_failed", request=req.id,
                error_type=type(exc).__name__, message=str(exc)[:200],
            )
        return out

    def forget(self, req: ExtractionRequest) -> None:
        """Back out an admit that never reached the queue (spool
        backpressure): the spool file stays on disk and will be
        re-submitted later under the SAME id, so no live record may
        linger to collide with it. The append-only manifest keeps the
        'queued' line and gains a non-terminal 'deferred' one — a later
        re-admit simply re-records."""
        with self._lock:
            self._records.pop(req.id, None)
            token = self._spans.pop(req.id, None)
            qtoken = self._qspans.pop(req.id, None)
        if qtoken is not None:
            qtoken.finish(state="deferred")
        if token is not None:
            token.finish(state="deferred")
        self._count("requests_deferred")
        self._record(f"request:{req.id}", "deferred")

    def reject(self, req: ExtractionRequest, reason: str) -> Dict[str, Any]:
        """Backpressure / bad-input terminal state: the request never
        reached the admission queue."""
        return self.finish(
            req, "rejected", error_class="rejected", message=reason
        )

    def requeue(self, req: ExtractionRequest, spool_dir: str) -> None:
        """Durably re-queue a spool-sourced request that this process
        cannot finish (shutdown with an undrained backlog, or crash
        recovery): write its payload back into the spool — atomically,
        like any producer — so the next daemon re-admits it under the
        same id, then drop the live record. The manifest gains a
        'requeued' line: non-terminal by design, because the spool file
        is now the durable owner of the request."""
        payload: Dict[str, Any] = {
            "feature_type": req.feature_type,
            "video_path": req.video_path,
            "id": req.id,
        }
        if req.bucket != DEFAULT_BUCKET:
            payload["bucket"] = req.bucket
        if req.priority:
            payload["priority"] = int(req.priority)
        if req.deadline_ms is not None:
            # the latency budget restarts on re-admission: a requeued
            # request gets a fresh window, not an instant expiry
            payload["deadline_ms"] = float(req.deadline_ms)
        atomic_write_json(os.path.join(spool_dir, f"{req.id}.json"), payload)
        with self._lock:
            self._records.pop(req.id, None)
            token = self._spans.pop(req.id, None)
            qtoken = self._qspans.pop(req.id, None)
        if qtoken is not None:
            qtoken.finish(state="requeued")
        if token is not None:
            token.finish(state="requeued")
        self._count("requests_requeued")
        self._record(f"request:{req.id}", "requeued")

    # -- crash recovery + retention -------------------------------------

    def reconcile(
        self,
        spool_dir: Optional[str] = None,
        live_replicas: Optional[set] = None,
        require_replica: bool = False,
    ) -> Dict[str, int]:
        """Pass over prior/peer processes' request manifests: every
        request a dead daemon left non-terminal (queued/dispatched)
        reaches a durable state — re-queued into the spool when it came
        from one (and a spool is configured), else marked ``failed`` /
        interrupted with a result record the status endpoint can serve.

        Single-replica (both fleet arguments at their defaults) this is
        the startup pass it has always been: it runs before any source
        opens, so every folded record belongs to a previous process.
        Fleet mode (ISSUE 18): ``live_replicas`` is the set of replica
        ids with a fresh heartbeat — a request whose latest manifest line
        is attributed to a LIVE peer is skipped (it is that peer's
        in-flight work, not a casualty); ``require_replica=True`` (the
        survivors' periodic sweep) additionally skips records with no
        replica attribution at all, because mid-flight there is no way
        to tell an unattributed live request from a dead one — only the
        startup pass, which runs before any source opens, may disposition
        those legacy records."""
        folded: Dict[str, Dict[str, Any]] = {}
        for r in faults_mod.iter_manifest_records(self.results_dir):
            key = r.get("video")
            if not isinstance(key, str) or not key.startswith("request:"):
                continue
            rid = key[len("request:"):]
            cur = folded.setdefault(rid, {})
            status = r.get("status")
            if status:
                cur["state"] = status
                # attribution follows the state: the replica that wrote
                # the LATEST transition owns the request now (a requeued
                # request re-admitted elsewhere belongs to its new home)
                if r.get("replica") is not None:
                    cur["replica"] = r["replica"]
            for f in ("feature_type", "video_path", "bucket", "source",
                      "priority", "deadline_ms"):
                if r.get(f) is not None:
                    cur.setdefault(f, r[f])
        requeued = interrupted = 0
        for rid, rec in sorted(folded.items()):
            state = rec.get("state")
            if state in TERMINAL_STATES or state in _SPOOL_SAFE_STATES:
                continue
            owner = rec.get("replica")
            if owner is None and require_replica:
                continue
            if live_replicas is not None and owner is not None \
                    and owner in live_replicas:
                continue
            req = ExtractionRequest(
                feature_type=str(rec.get("feature_type") or ""),
                video_path=str(rec.get("video_path") or ""),
                id=rid,
                bucket=str(rec.get("bucket") or DEFAULT_BUCKET),
                source=str(rec.get("source") or "local"),
                priority=int(rec.get("priority") or 0),
                deadline_ms=rec.get("deadline_ms"),
            )
            if req.source == "spool" and spool_dir:
                self.requeue(req, spool_dir)
                requeued += 1
            else:
                self.finish(
                    req, "failed", error_class="interrupted",
                    message=f"daemon terminated while request was {state}; "
                            "resubmit to retry",
                )
                interrupted += 1
        return {"requeued": requeued, "interrupted": interrupted}

    def sweep(
        self,
        ttl_s: float,
        max_records: int,
        now: Optional[float] = None,
    ) -> int:
        """TTL/size-bounded retention: prune terminal result files (and
        prior-run manifest event files) older than ``ttl_s``, keep at
        most ``max_records`` result files (oldest dropped first), and
        age the in-memory map the same way — ``_requests/`` stops
        growing without bound under steady traffic. Returns how many
        records were pruned."""
        now = time.time() if now is None else now
        pruned = 0
        results: List[Tuple[float, str]] = []
        try:
            names = os.listdir(self.results_dir)
        except OSError:
            names = []
        for name in names:
            if not name.endswith(".json"):
                continue
            path = os.path.join(self.results_dir, name)
            try:
                if os.path.isfile(path):
                    results.append((os.stat(path).st_mtime, path))
            except OSError:
                continue
        results.sort()  # oldest first
        survivors: List[str] = []
        for mtime, path in results:
            if ttl_s > 0 and now - mtime > ttl_s:
                pruned += self._unlink(path)
            else:
                survivors.append(path)
        if max_records > 0 and len(survivors) > max_records:
            for path in survivors[: len(survivors) - max_records]:
                pruned += self._unlink(path)
        if ttl_s > 0:
            # prior-run manifest logs: after reconcile() every request
            # they describe is terminal (and result-file-backed), so an
            # aged-out events file carries no live state
            for path in glob.glob(
                os.path.join(self.results_dir, faults_mod.MANIFEST_DIRNAME,
                             "events-*.jsonl")
            ):
                if path == self.manifest.path:
                    continue
                try:
                    if now - os.stat(path).st_mtime > ttl_s:
                        pruned += self._unlink(path)
                except OSError:
                    continue
        with self._lock:
            terminal = sorted(
                (rec.get("finished_ts", 0.0), rid)
                for rid, rec in self._records.items()
                if rec.get("state") in TERMINAL_STATES
            )
            drop = [rid for ts, rid in terminal if ttl_s > 0 and now - ts > ttl_s]
            keep = len(terminal) - len(drop)
            if max_records > 0 and keep > max_records:
                dropped = set(drop)
                drop += [rid for ts, rid in terminal
                         if rid not in dropped][: keep - max_records]
            for rid in drop:
                self._records.pop(rid, None)
        return pruned + len(drop)

    @staticmethod
    def _unlink(path: str) -> int:
        try:
            os.unlink(path)
            return 1
        except OSError:
            return 0

    # -- queries --------------------------------------------------------

    def get(self, request_id: str) -> Optional[Dict[str, Any]]:
        """The live record, falling back to the durable result file for
        requests finished before a daemon restart."""
        with self._lock:
            rec = self._records.get(request_id)
            if rec is not None:
                return dict(rec)
        if not _ID_RE.match(request_id or ""):
            return None
        path = os.path.join(self.results_dir, f"{request_id}.json")
        try:
            with open(path, "r", encoding="utf-8") as fh:
                return json.load(fh)
        except (OSError, ValueError):
            return None

    def counts(self) -> Dict[str, int]:
        with self._lock:
            out: Dict[str, int] = {s: 0 for s in REQUEST_STATES}
            for rec in self._records.values():
                s = rec.get("state")
                if s in out:
                    out[s] += 1
        return out

    # -- internals ------------------------------------------------------

    def _count(self, name: str) -> None:
        if self.telemetry is not None and self.telemetry.enabled:
            self.telemetry.metrics.inc(name)

    def _record(self, key: str, status: str, **extra: Any) -> None:
        if self.replica_id is not None:
            extra.setdefault("replica", self.replica_id)
        self.manifest.record(key, status, **extra)

    def _write_result(self, rec: Dict[str, Any]) -> None:
        """tmp + rename so a status reader never sees a torn record."""
        faults_mod.fire("tracker_write")
        path = os.path.join(self.results_dir, f"{rec['id']}.json")
        atomic_write_json(path, rec, indent=1, sort_keys=True)
