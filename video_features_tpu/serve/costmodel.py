"""Online group service-time estimation for cost-aware scheduling.

The ROADMAP's cost-model item: the EDF scheduler (serve/scheduler.py)
ranks every ready group as if service time were equal, so a cheap
tier-0 group never slots into the slack before an expensive deadline
group. :class:`ServiceTimeModel` closes that gap — an online estimator
of fused-group service time, fed by the dispatcher from the completed
group spans it already times (the same intervals the
``group_service_s.<feature_type>|<bucket>`` histograms record), and
consulted by the ``edf-cost`` scheduler's feasibility ranking.

Estimation is deliberately simple (Arachne's cascade-orchestration
point is that *any* calibrated cost beats assuming uniform cost):

- per (feature_type, bucket) key, an EWMA of **per-item** service
  seconds (group seconds / group size), so group-size scaling is
  linear: ``predict(key, n) = ewma_per_item * n``;
- fallback hierarchy when a key has no observations yet: the feature
  type's own aggregate across buckets, then the feature type's weight
  class (:func:`weight_class` — light/medium/heavy, a static prior over
  model families), then the global aggregate, then 0.0 — and a 0.0
  prediction makes ``edf-cost`` rank exactly like plain EDF, so a cold
  daemon degrades to the proven baseline instead of guessing;
- persistence: a JSON file next to the compile cache (the other
  warm-start artifact), loaded at construction and rewritten atomically
  (throttled) so a restarted daemon schedules with yesterday's costs
  from its first request.

Thread-safety: `observe`/`predict` run on the dispatcher and scheduler
paths under the batcher's condition variable; all state here is behind
one lock with no I/O inside it (GC311/GC312) — :meth:`save` snapshots
under the lock and writes outside it. No jax imports.
"""

from __future__ import annotations

import json
import os
import threading
from typing import Any, Dict, Optional, Tuple, Union

# Static priors over model families: the coarse cost tier a feature
# type starts in before its own observations arrive. Heavy = per-frame
# optical flow / 3D convs; light = small CNN / audio; medium = the rest.
WEIGHT_CLASSES: Dict[str, str] = {
    "resnet18": "light",
    "resnet34": "light",
    "resnet50": "medium",
    "resnet101": "heavy",
    "resnet152": "heavy",
    "CLIP-ViT-B/32": "medium",
    "CLIP-ViT-B/16": "heavy",
    "CLIP4CLIP-ViT-B-32": "medium",
    "i3d": "heavy",
    "r21d_rgb": "heavy",
    "raft": "heavy",
    "pwc": "heavy",
    "vggish": "light",
    "vggish_torch": "light",
}

MODEL_FILENAME = "service_time_model.json"
SCHEMA_VERSION = 1

Key = Union[str, Tuple[str, str]]


def weight_class(feature_type: str) -> str:
    return WEIGHT_CLASSES.get(feature_type, "medium")


def default_model_path(cfg: Any) -> str:
    """Where the estimator persists: next to the compile cache when one
    is configured (both are warm-start state a restart should reuse),
    else under the run's ``_telemetry`` directory."""
    cache = getattr(cfg, "compile_cache", None)
    if cache:
        return os.path.join(cache, MODEL_FILENAME)
    return os.path.join(cfg.output_path, "_telemetry", MODEL_FILENAME)


def _key_str(key: Key) -> str:
    if isinstance(key, str):
        return key
    ft, bucket = key
    return f"{ft}|{bucket}"


class _Ewma:
    __slots__ = ("value", "n")

    def __init__(self, value: float = 0.0, n: int = 0) -> None:
        self.value = float(value)
        self.n = int(n)

    def update(self, x: float, alpha: float) -> None:
        self.value = x if self.n == 0 else alpha * x + (1.0 - alpha) * self.value
        self.n += 1


class ServiceTimeModel:
    """Per-(feature_type, bucket) EWMA of per-item group service time
    with feature-type / weight-class / global fallbacks. See module
    docstring for the estimation and persistence contract."""

    def __init__(
        self,
        path: Optional[str] = None,
        alpha: float = 0.25,
        save_every: int = 16,
    ) -> None:
        self.path = path
        self.alpha = float(alpha)
        self.save_every = max(int(save_every), 1)
        self._lock = threading.Lock()
        self._keys: Dict[str, _Ewma] = {}
        self._fts: Dict[str, _Ewma] = {}
        self._classes: Dict[str, _Ewma] = {}
        self._global = _Ewma()
        self._observations = 0
        self._dirty = 0
        if path is not None:
            self._load(path)

    # -- the write side (dispatcher thread) ------------------------------

    def observe(
        self, feature_type: str, bucket: str, group_size: int, seconds: float
    ) -> None:
        """Fold one completed group's wall seconds in; throttled
        auto-save when a path is configured (file write happens outside
        the model lock)."""
        if seconds < 0 or group_size < 1:
            return
        per_item = float(seconds) / max(int(group_size), 1)
        save_now = False
        with self._lock:
            self._keys.setdefault(_key_str((feature_type, bucket)), _Ewma()) \
                .update(per_item, self.alpha)
            self._fts.setdefault(feature_type, _Ewma()).update(per_item, self.alpha)
            self._classes.setdefault(weight_class(feature_type), _Ewma()) \
                .update(per_item, self.alpha)
            self._global.update(per_item, self.alpha)
            self._observations += 1
            self._dirty += 1
            if self.path is not None and self._dirty >= self.save_every:
                self._dirty = 0
                save_now = True
        if save_now:
            self.save()

    # -- the read side (scheduler rank, /v1/stats) -----------------------

    def predict(self, key: Key, group_size: int) -> float:
        """Predicted service seconds for a group of ``group_size`` at
        ``key`` (``(feature_type, bucket)`` or the ``"ft|bucket"``
        string). 0.0 when nothing relevant has been observed — the
        edf-cost scheduler then ranks exactly like plain EDF."""
        ks = _key_str(key)
        ft = ks.split("|", 1)[0]
        with self._lock:
            for est in (
                self._keys.get(ks),
                self._fts.get(ft),
                self._classes.get(weight_class(ft)),
                self._global,
            ):
                if est is not None and est.n > 0:
                    return est.value * max(int(group_size), 1)
        return 0.0

    def observations(self) -> int:
        with self._lock:
            return self._observations

    def snapshot(self) -> Dict[str, Any]:
        """The /v1/stats block: per-key per-item estimates + fallbacks."""
        with self._lock:
            return {
                "observations": self._observations,
                "keys": {
                    k: {"per_item_s": round(e.value, 6), "n": e.n}
                    for k, e in sorted(self._keys.items())
                },
                "feature_types": {
                    k: {"per_item_s": round(e.value, 6), "n": e.n}
                    for k, e in sorted(self._fts.items())
                },
                "weight_classes": {
                    k: {"per_item_s": round(e.value, 6), "n": e.n}
                    for k, e in sorted(self._classes.items())
                },
                "global": {"per_item_s": round(self._global.value, 6),
                           "n": self._global.n},
            }

    # -- persistence ------------------------------------------------------

    def save(self, path: Optional[str] = None) -> Optional[str]:
        """Atomic rewrite of the persistence file. Snapshot under the
        lock, write outside it (GC312: no blocking I/O under a lock on
        the dispatch path). Returns the path written, or None."""
        path = path or self.path
        if path is None:
            return None
        with self._lock:
            doc = {
                "version": SCHEMA_VERSION,
                "alpha": self.alpha,
                "observations": self._observations,
                "keys": {k: [e.value, e.n] for k, e in self._keys.items()},
                "feature_types": {k: [e.value, e.n] for k, e in self._fts.items()},
                "weight_classes": {k: [e.value, e.n] for k, e in self._classes.items()},
                "global": [self._global.value, self._global.n],
            }
        from video_features_tpu.io.sink import atomic_write_json

        return atomic_write_json(path, doc)

    def _load(self, path: str) -> None:
        try:
            with open(path, "r", encoding="utf-8") as f:
                doc = json.load(f)
        except (OSError, ValueError):
            return  # no/torn prior state: start cold
        if not isinstance(doc, dict) or doc.get("version") != SCHEMA_VERSION:
            return

        def fold(src: Any) -> Dict[str, _Ewma]:
            out: Dict[str, _Ewma] = {}
            if isinstance(src, dict):
                for k, pair in src.items():
                    try:
                        v, n = float(pair[0]), int(pair[1])
                    except (TypeError, ValueError, IndexError):
                        continue
                    if n > 0 and v >= 0:
                        out[str(k)] = _Ewma(v, n)
            return out

        with self._lock:
            self._keys = fold(doc.get("keys"))
            self._fts = fold(doc.get("feature_types"))
            self._classes = fold(doc.get("weight_classes"))
            g = doc.get("global")
            try:
                self._global = _Ewma(float(g[0]), int(g[1]))
            except (TypeError, ValueError, IndexError):
                self._global = _Ewma()
            self._observations = int(doc.get("observations") or 0)
