"""Cross-key dispatch ordering for the serve daemon (ISSUE 8 tentpole).

PR 7's dispatcher was FIFO over ready groups: whichever (feature_type,
bucket) buffer filled or timed out first ran first, regardless of which
request was about to miss its deadline. This module owns the dispatch
ORDER across keys (the VirtualFlow framing: the scheduler, not the
extractor, decides what reaches the chip next), implementing
earliest-effective-deadline-first with priority tiers and
anti-starvation aging:

- every request carries an optional ``deadline_ms`` (stamped to an
  absolute ``deadline_at`` on the admission clock when admitted) and a
  ``priority`` tier (0..9, higher = more urgent);
- a ready group's *effective deadline* is the earliest deadline of its
  members; deadline-less members count as ``admitted_at +
  default_slack_s``, so best-effort traffic still ages toward the front
  instead of starving behind an endless deadline stream;
- groups rank by ``(effective priority tier desc, effective deadline
  asc, arrival)``; a group's tier is its most urgent member's, boosted
  one tier per ``aging_s`` its oldest member has waited — so a tier-0
  backlog can never be starved by a steady tier-9 stream (after at most
  ``9 * aging_s`` of waiting, any group reaches the top tier).

Everything here is a pure function of ``(groups, now)``: the batcher
calls :meth:`pick` under its own lock with its own (injectable) clock,
and the fake-clock tier-1 tests plus the ``serve_scheduling`` bench
part drive the same code with synthetic groups — no threads, no sleeps.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple, Union

# a group, as the batcher stores it: ((feature_type, bucket), [requests]).
# Duplicated shape (not imported from batcher) to keep this module
# import-light and cycle-free — batcher imports the scheduler.
Group = Tuple[Tuple[str, str], List[Any]]

# aging can promote a group at most this many tiers past its declared
# priority: enough to clear the 0..9 request range with room to spare,
# finite so an infinitely-old group (or a now=inf drain sweep) ranks
# deterministically instead of overflowing
MAX_AGING_BOOST = 16

SCHEDULER_NAMES = ("edf", "fifo", "edf-cost")


class EdfScheduler:
    """Earliest-effective-deadline-first across (feature_type, bucket)
    keys, with priority tiers and aging. Stateless between calls: rank
    is recomputed at each pick so aging reflects *dispatch-time* wait,
    not admission-time."""

    name = "edf"

    def __init__(self, default_slack_s: float = 30.0, aging_s: float = 10.0) -> None:
        self.default_slack_s = max(float(default_slack_s), 0.0)
        self.aging_s = float(aging_s)

    # -- rank components -------------------------------------------------

    def effective_deadline(self, requests: Sequence[Any], now: float) -> float:
        """Earliest member deadline; deadline-less members count as
        ``admitted_at + default_slack_s`` so they participate in EDF
        instead of sorting last forever."""
        best: float = float("inf")
        for r in requests:
            d = getattr(r, "deadline_at", None)
            if d is None:
                t0 = getattr(r, "admitted_at", None)
                d = (now if t0 is None else t0) + self.default_slack_s
            if d < best:
                best = d
        return now if best == float("inf") else best

    def _aging_boost(self, requests: Sequence[Any], now: float) -> int:
        if self.aging_s <= 0:
            return 0
        oldest = min(
            (t for r in requests
             if (t := getattr(r, "admitted_at", None)) is not None),
            default=None,
        )
        if oldest is None:
            return 0
        wait = now - oldest
        if wait >= self.aging_s * MAX_AGING_BOOST:
            return MAX_AGING_BOOST
        return int(wait / self.aging_s) if wait > 0 else 0

    def rank(self, group: Group, now: float) -> Tuple[float, float]:
        """Smaller ranks dispatch first. Priority tier (aged) dominates;
        effective deadline breaks ties within a tier; callers break
        remaining ties by arrival order (stable index)."""
        _key, requests = group
        tier = max((int(getattr(r, "priority", 0) or 0) for r in requests), default=0)
        tier += self._aging_boost(requests, now)
        return (-float(tier), self.effective_deadline(requests, now))

    # -- the batcher's surface -------------------------------------------

    def pick(self, groups: Sequence[Group], now: float) -> int:
        """Index of the group to dispatch next (``groups`` non-empty;
        index tie-break = arrival order, since the batcher appends ready
        groups in the order they became ready)."""
        return min(range(len(groups)), key=lambda i: (self.rank(groups[i], now), i))

    def order(self, groups: Sequence[Group], now: float) -> List[Group]:
        """All groups, best-first — the inline-drain and test surface."""
        idx = sorted(range(len(groups)), key=lambda i: (self.rank(groups[i], now), i))
        return [groups[i] for i in idx]


class FifoScheduler(EdfScheduler):
    """PR 7's dispatch order (arrival only), kept as the A/B baseline
    the ``serve_scheduling`` bench part and the EDF-beats-FIFO
    acceptance test compare against."""

    name = "fifo"

    def rank(self, group: Group, now: float) -> Tuple[float, float]:
        return (0.0, 0.0)  # callers' index tie-break IS the order


class CostAwareEdfScheduler(EdfScheduler):
    """EDF with a calibrated service-time model (``--scheduler
    edf-cost``): rank by *latest feasible start time* and demote groups
    that cannot meet their deadline anyway.

    Plain EDF's overload pathology on a serial non-preemptive machine:
    the earliest deadline may belong to a group so expensive it is
    already doomed — running it first burns its whole service time AND
    dominoes every cheap group behind it past their own deadlines. Note
    that pure least-laxity (``deadline - predicted``) makes this
    *worse*: a doomed expensive group has the most negative laxity, so
    it ranks MORE urgent, and total work is conserved — reordering only
    renames which requests miss. The win comes from feasibility:

    - a group is **doomed** when ``now + predicted_service`` already
      exceeds its earliest *declared* member deadline (slack-derived
      effective deadlines never doom a group — missing them is a
      soft ordering preference, not a contract);
    - feasible groups rank by (aged priority tier desc, latest start
      time ``effective_deadline - predicted_service`` asc) — the group
      that must start soonest to still make it goes first, which is
      exactly EDF when predictions are equal (and exactly EDF with 0.0
      predictions, i.e. a cold :class:`~video_features_tpu.serve.
      costmodel.ServiceTimeModel`);
    - doomed groups sort behind every feasible group (still mutually
      ordered by tier + latest-start), so their members expire at the
      dispatch boundary or run late — after the work that can still
      meet its promises.

    The model's ``predict`` is consulted under the batcher's condition
    variable; it takes only the model's own lock and does no I/O
    (GC311: the nesting batcher-cond -> model-lock is acyclic — nothing
    in costmodel calls back into the batcher)."""

    name = "edf-cost"

    def __init__(
        self,
        cost_model: Any,
        default_slack_s: float = 30.0,
        aging_s: float = 10.0,
    ) -> None:
        super().__init__(default_slack_s=default_slack_s, aging_s=aging_s)
        self.cost_model = cost_model

    def predicted_service_s(self, group: Group, now: float) -> float:
        key, requests = group
        try:
            return max(float(self.cost_model.predict(key, len(requests)) or 0.0), 0.0)
        except Exception:  # noqa: BLE001 - a broken model must not stop dispatch
            return 0.0

    @staticmethod
    def _earliest_declared_deadline(requests: Sequence[Any]) -> Optional[float]:
        best: Optional[float] = None
        for r in requests:
            d = getattr(r, "deadline_at", None)
            if d is not None and (best is None or d < best):
                best = d
        return best

    def rank(self, group: Group, now: float) -> Tuple[float, float, float]:
        neg_tier, eff_deadline = super().rank(group, now)
        pred = self.predicted_service_s(group, now)
        declared = self._earliest_declared_deadline(group[1])
        doomed = 1.0 if (
            pred > 0.0 and declared is not None and now + pred > declared
        ) else 0.0
        return (doomed, neg_tier, eff_deadline - pred)


def build_scheduler(
    name: str,
    default_slack_s: float = 30.0,
    aging_s: float = 10.0,
    cost_model: Any = None,
) -> EdfScheduler:
    if name not in SCHEDULER_NAMES:
        raise ValueError(f"unknown scheduler {name!r} (expected one of {SCHEDULER_NAMES})")
    if name == "edf-cost":
        if cost_model is None:
            from video_features_tpu.serve.costmodel import ServiceTimeModel

            cost_model = ServiceTimeModel()
        return CostAwareEdfScheduler(
            cost_model, default_slack_s=default_slack_s, aging_s=aging_s
        )
    cls = FifoScheduler if name == "fifo" else EdfScheduler
    return cls(default_slack_s=default_slack_s, aging_s=aging_s)


def simulate_dispatch(
    groups: Sequence[Group],
    scheduler: EdfScheduler,
    service_s: Union[float, Callable[[Tuple[str, str], Sequence[Any]], float]],
    start: float = 0.0,
) -> List[Dict[str, Any]]:
    """Deterministic serial-dispatch simulation over ready groups: one
    group per ``service_s`` tick, ordered by ``scheduler.pick`` at each
    tick (so aging acts over simulated time). ``service_s`` may be a
    constant or a ``(key, requests) -> seconds`` callable — the
    heterogeneous-cost burst the edf-cost acceptance test and the
    ``serve_cost_model`` bench part replay. Returns one record per
    request with its completion time, latency, and whether its deadline
    was met — shared by the pinned scheduler tier-1 tests and the bench
    parts, so the benched policy is exactly the tested one."""
    pending: List[Group] = list(groups)
    now = float(start)
    out: List[Dict[str, Any]] = []
    while pending:
        i = scheduler.pick(pending, now)
        key, requests = pending.pop(i)
        now += float(
            service_s(key, requests) if callable(service_s) else service_s
        )
        for r in requests:
            deadline = getattr(r, "deadline_at", None)
            admitted = getattr(r, "admitted_at", None)
            out.append({
                "id": getattr(r, "id", None),
                "key": key,
                "priority": int(getattr(r, "priority", 0) or 0),
                "completed_at": now,
                "latency_s": now - (start if admitted is None else admitted),
                "met": deadline is None or now <= deadline,
            })
    return out
