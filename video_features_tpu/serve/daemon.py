"""The long-lived extraction daemon: resident models, warm executables,
request sources, and the ``serve`` CLI entry.

Pieces (each its own module, wired here):

- :class:`ExtractorPool` — one resident ``BaseExtractor`` per served
  feature type, built lazily and kept for the daemon's lifetime: weights
  load once, the per-bucket fused executables and ``--compile_cache``
  entries stay warm, and every group dispatch rides the existing
  ``extract/base.py`` group path (device preprocess, graceful
  degradation, classified retries — all per request, for free).
- :class:`~video_features_tpu.serve.batcher.AdmissionController` — the
  bucket-keyed coalescing queue (bounded; the backpressure contract).
- :class:`~video_features_tpu.serve.lifecycle.RequestTracker` — the
  manifest-backed queued/dispatched/done|failed record per request.
- sources — HTTP (:mod:`.server`) and the spool directory
  (:mod:`.sources`), both funneling into :meth:`ServeDaemon.submit`.

``serve warmup`` (or ``--warmup`` with traffic) pre-builds the fused
executables for declared (feature_type, WxH bucket) pairs by driving a
synthetic clip of exactly that resolution through the normal dispatch
path — against ``--compile_cache`` the daemon's first real requests then
never eat a compile, and RecompileWatch warnings (armed per extractor
under ``--preprocess device``) land in the daemon's manifest log.
"""

from __future__ import annotations

import os
import signal
import threading
import time
import traceback
import uuid
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

from video_features_tpu.config import (
    ExtractionConfig,
    ServeConfig,
    sanity_check,
)
from video_features_tpu.extract.registry import build_extractor
from video_features_tpu.io.sink import expected_output_files
from video_features_tpu.runtime import faults
from video_features_tpu.runtime import telemetry as telemetry_mod
from video_features_tpu.runtime.telemetry import SloTracker, Telemetry
from video_features_tpu.serve.batcher import AdmissionController, Key, QueueFull
from video_features_tpu.serve.costmodel import ServiceTimeModel, default_model_path
from video_features_tpu.serve.lifecycle import (
    TERMINAL_STATES,
    BadRequest,
    ExtractionRequest,
    InvalidMedia,
    ReplicaRegistry,
    RequestTracker,
    parse_request,
)
from video_features_tpu.serve.preemptor import PreemptionPlan, Preemptor
from video_features_tpu.serve.scheduler import build_scheduler
from video_features_tpu.serve.supervisor import (
    CircuitBreaker,
    GroupTimeout,
    ModelUnavailable,
    Watchdog,
)
from video_features_tpu.telemetry.exposition import (
    Family,
    families_from_ledger,
    families_from_snapshot,
    group_service_metric,
    render_families,
)
from video_features_tpu.telemetry.ledger import (
    CostLedger,
    DeviceMemorySampler,
    default_ledger_path,
    format_bytes,
)


class _OutcomeTee:
    """Wraps an extractor's manifest: every record still reaches the real
    per-video manifest; terminal per-video records (done/failed) are
    additionally captured so the dispatcher can map them back to the
    requests of the group it just ran. Lock-guarded — records arrive
    from decode workers and the dispatcher thread alike."""

    def __init__(self, inner: Any) -> None:
        self._inner = inner
        self._lock = threading.Lock()
        self._outcomes: Dict[str, Dict[str, Any]] = {}

    @property
    def path(self):  # cli.py probes manifest.path before finalizing
        return self._inner.path

    @property
    def output_root(self):
        return self._inner.output_root

    def record(self, video: Any, status: str, **kw: Any) -> None:
        self._inner.record(video, status, **kw)
        if status in ("done", "failed"):
            with self._lock:
                self._outcomes[str(video)] = {"status": status, **kw}

    def event(self, name: str, **fields: Any) -> None:
        self._inner.event(name, **fields)

    def take(self) -> Dict[str, Dict[str, Any]]:
        """Drain the outcomes captured since the last call (the
        dispatcher calls this once per group, on its own thread)."""
        with self._lock:
            out, self._outcomes = self._outcomes, {}
        return out


class ExtractorPool:
    """Resident extractors, one per feature type, built once and reused
    for every subsequent request — the warm state a daemon exists to
    keep (no process startup, no weight reload, no re-jit)."""

    def __init__(
        self,
        cfg: ExtractionConfig,
        max_group_size: int,
        build: Callable[..., Any] = build_extractor,
        clock: Callable[[], float] = time.monotonic,
    ) -> None:
        self._cfg = cfg
        self._max_group_size = max(int(max_group_size), 1)
        self._build = build
        self._clock = clock
        self._lock = threading.Lock()
        self._extractors: Dict[str, Any] = {}
        # per-feature-type build latch: the winning builder publishes and
        # sets it; losers wait OUTSIDE the pool lock (see get())
        self._building: Dict[str, threading.Event] = {}
        self.build_count: Dict[str, int] = {}
        # when each resident was (re)built, on the daemon's clock — the
        # preemptor's min-residency guard reads this (ISSUE 18)
        self.built_at: Dict[str, float] = {}

    def _serving_config(self, feature_type: str) -> ExtractionConfig:
        """The per-feature-type extraction config: the daemon's base
        flags with the serve invariants pinned (save outputs, no resume
        probing, group size = the admission group bound, and at least
        one decode worker so the fused group path is reachable)."""
        cfg = self._cfg.replace(
            feature_type=feature_type,
            video_paths=[],
            flow_paths=None,
            file_with_video_paths=None,
            video_dir=None,
            flow_dir=None,
            on_extraction=(
                self._cfg.on_extraction
                if self._cfg.on_extraction in ("save_numpy", "save_pickle")
                else "save_numpy"
            ),
            video_batch=self._max_group_size,
            decode_workers=max(int(self._cfg.decode_workers or 0), 1),
            resume=False,
            retry_failed=False,
            strict=False,
            show_pred=False,
        )
        return sanity_check(cfg)

    def get(self, feature_type: str) -> Any:
        """Return the resident extractor, building it on first use.

        The build (weights load + first jit compile) can take tens of
        seconds and runs OUTSIDE ``_lock`` — GC312: anything queued on
        the pool lock (``status()`` -> :meth:`feature_types`, eviction)
        must never block behind it. One build per feature type is
        serialized through a per-type latch; concurrent callers wait on
        the latch (timed, off-lock) and re-check. A failed build clears
        the latch so the next caller retries from scratch."""
        while True:
            with self._lock:
                ext = self._extractors.get(feature_type)
                if ext is not None:
                    return ext
                latch = self._building.get(feature_type)
                builder = latch is None
                if builder:
                    latch = self._building[feature_type] = threading.Event()
            if not builder:
                latch.wait(1.0)  # poll: a crashed builder clears the latch
                continue
            try:
                ext = self._build(self._serving_config(feature_type))
                ext.manifest = _OutcomeTee(ext.manifest)
                with self._lock:
                    self._extractors[feature_type] = ext
                    self.build_count[feature_type] = (
                        self.build_count.get(feature_type, 0) + 1
                    )
                    self.built_at[feature_type] = self._clock()
                return ext
            finally:
                with self._lock:
                    self._building.pop(feature_type, None)
                latch.set()

    def feature_types(self) -> List[str]:
        with self._lock:
            return sorted(self._extractors)

    def evict(self, feature_type: str) -> None:
        """Tear one resident extractor down (breaker opened, or a
        watchdog-abandoned worker may still hold its model state); the
        next :meth:`get` rebuilds from scratch through the same path —
        warm compile cache, fresh everything else."""
        with self._lock:
            ext = self._extractors.pop(feature_type, None)
            self.built_at.pop(feature_type, None)
        if ext is not None:
            try:
                ext.telemetry.close()
            except Exception:  # noqa: BLE001 - eviction must finish
                pass

    def close(self) -> None:
        with self._lock:
            exts = list(self._extractors.values())
        for ext in exts:
            try:
                ext.telemetry.close()
            except Exception:  # noqa: BLE001 - shutdown must finish
                pass


class ServeDaemon:
    """The daemon: glue between sources, admission, the pool, and the
    request tracker. Construct, :meth:`start`, then :meth:`shutdown`
    (drains by default)."""

    def __init__(
        self,
        scfg: ServeConfig,
        build: Callable[..., Any] = build_extractor,
        clock: Callable[[], float] = time.monotonic,
    ) -> None:
        self.scfg = scfg
        self.cfg = scfg.extraction
        self.clock = clock
        os.makedirs(self.cfg.output_path, exist_ok=True)
        # serve-path stages (admission/serve_dispatch/tracker_write) fire
        # before any extractor exists; install the injector now — each
        # extractor build reinstalls the same specs (extract/base.py),
        # which only resets the counters
        faults.install_injector(self.cfg.fault_inject)
        # the daemon's own telemetry: request spans, admission gauge,
        # request counters, and the heartbeat line (which now reports
        # live queue depth — see Telemetry.heartbeat_line)
        self.telemetry = Telemetry(
            output_root=self.cfg.output_path,
            enabled=self.cfg.telemetry != "off",
            heartbeat_s=float(self.cfg.heartbeat_s or 0.0),
        )
        # the serve heartbeat replaces the batch-oriented default line
        # (videos/s, ETA) with queue depth / inflight / miss rate
        self.telemetry.heartbeat_provider = self._heartbeat_line
        self._start_mono = clock()
        self._hb_prev: Tuple[float, int] = (clock(), 0)
        # rolling SLO window + the online service-time estimator; both
        # live on the daemon's (injectable) scheduling clock
        self.slo = SloTracker(window_s=scfg.slo_window_s, clock=clock)
        self.cost_model = ServiceTimeModel(path=default_model_path(self.cfg))
        # device cost ledger: the pooled extractors record every built
        # executable's cost/memory analysis here (extract/base.py wraps
        # state callables on warmup); shared() so daemon and extractors
        # see one object per path. The sampler polls device.memory_stats
        # into the registry (absent on backends without the API, e.g. CPU)
        self.ledger = CostLedger.shared(default_ledger_path(self.cfg))
        self.sampler = DeviceMemorySampler(
            self.telemetry.metrics,
            interval_s=max(float(self.cfg.heartbeat_s or 0.0), 10.0),
        )
        # fleet identity (ISSUE 18): every manifest line is attributed
        # to this replica, and the registry heartbeat is how surviving
        # peers on a shared output store learn this process is alive
        self.replica_id = scfg.resolved_replica_id()
        self.registry = ReplicaRegistry(self.cfg.output_path, self.replica_id)
        self.registry.beat()
        self.tracker = RequestTracker(
            self.cfg.output_path, telemetry=self.telemetry,
            slo=self.slo, clock=clock, replica_id=self.replica_id,
        )
        # crash recovery BEFORE any source can admit: requests a dead
        # process left queued/dispatched reach a durable state (spool
        # files re-queued, HTTP requests failed 'interrupted'). In a
        # fleet (lease_timeout_s > 0) LIVE peers' in-flight requests are
        # not casualties — skip them; our own prior incarnation is never
        # "live" to us at startup, so a same-id restart still recovers.
        live_peers = None
        if scfg.lease_timeout_s > 0:
            live_peers = (
                self.registry.live(scfg.lease_timeout_s) - {self.replica_id}
            )
        self.recovered = self.tracker.reconcile(
            scfg.spool_dir, live_replicas=live_peers
        )
        if any(self.recovered.values()):
            print(f"serve: recovered prior run: {self.recovered['requeued']} "
                  f"requeued, {self.recovered['interrupted']} interrupted")
        self.tracker.sweep(scfg.request_ttl_s, scfg.max_request_records)
        # admission preflight (--preflight on): one caps snapshot shared
        # by every submit; the extractors re-derive the same caps from
        # the same config at build time (extract/base.py)
        from video_features_tpu.io.probe import ResourceCaps

        self._caps = ResourceCaps.from_config(self.cfg)
        self.pool = ExtractorPool(
            self.cfg, scfg.max_group_size, build=build, clock=clock
        )
        # content-addressed feature cache (extract/cache.py): a repeat
        # request for an already-extracted (content, config) pair goes
        # terminal 'done' at admission — no queue, no decode, no chip.
        # Misses populate the store through the pooled extractors' sink
        # path (extract/base.py carries the same cache_dir).
        self.cache: Any = None
        self._cache_keys: Dict[str, tuple] = {}  # ft -> (digest, keys, out, mode, direct)
        if getattr(self.cfg, "cache_dir", None):
            from video_features_tpu.extract.cache import FeatureCache

            self.cache = FeatureCache(
                self.cfg.cache_dir,
                hash_mode=getattr(self.cfg, "cache_hash", "fast") or "fast",
            )
        # shared-decode frame cache (extract/plan.py): a daemon serving
        # >1 model decodes each clip once and fans the frames out to
        # every resident extractor; installed for the daemon's lifetime,
        # uninstalled in shutdown()
        self._frame_cache: Any = None
        if len(scfg.feature_types) > 1:
            from video_features_tpu.extract.plan import cache_for
            from video_features_tpu.io.video import set_frame_cache

            self._frame_cache = cache_for(self.cfg, scfg.feature_types)
            if self._frame_cache is not None:
                set_frame_cache(self._frame_cache)
        self.batcher = AdmissionController(
            dispatch=self._dispatch_group,
            max_group_size=scfg.max_group_size,
            max_batch_wait_s=scfg.max_batch_wait_ms / 1000.0,
            max_queue=scfg.max_queue,
            clock=clock,
            metrics=self.telemetry.metrics,
            scheduler=build_scheduler(
                scfg.scheduler,
                default_slack_s=scfg.default_slack_ms / 1000.0,
                aging_s=scfg.aging_ms / 1000.0,
                cost_model=self.cost_model,
            ),
        )
        self.watchdog = Watchdog(scfg.group_timeout_s)
        self._breakers: Dict[str, CircuitBreaker] = {}
        # HBM-aware preemption (serve/preemptor.py): only constructed
        # when --preempt on; with it off, an overcommitting burst keeps
        # today's behavior (no admission HBM gate)
        self.preemptor: Optional[Preemptor] = None
        self._preempt_plans: Dict[str, PreemptionPlan] = {}
        if scfg.preempt == "on":
            self.preemptor = Preemptor(
                ledger=self.ledger,
                cost_model=self.cost_model,
                pool=self.pool,
                breaker_for=self._breaker,
                headroom_fn=self._headroom_bytes,
                queued_fn=self.batcher.queued_by_feature_type,
                hbm_budget_bytes=scfg.hbm_budget_bytes,
                cooldown_s=scfg.preempt_cooldown_s,
                min_residency_s=scfg.preempt_min_residency_s,
                clock=clock,
                metrics=(self.telemetry.metrics
                         if self.telemetry.enabled else None),
                manifest=self.tracker.manifest,
            )
        self._cancel_pending: set = set()
        self._http_server: Any = None
        self._http_thread: Any = None
        self._spool: Any = None
        self._sweep_thread: Optional[threading.Thread] = None
        self._sweep_stop = threading.Event()
        self._lock = threading.Lock()
        self._started = False

    def _breaker(self, feature_type: str) -> CircuitBreaker:
        with self._lock:
            b = self._breakers.get(feature_type)
            if b is None:
                b = CircuitBreaker(
                    threshold=self.scfg.breaker_threshold,
                    cooldown_s=self.scfg.breaker_cooldown_s,
                    clock=self.clock,
                )
                self._breakers[feature_type] = b
            return b

    # -- the request path ------------------------------------------------

    def submit(self, payload: Dict[str, Any], source: str) -> Dict[str, Any]:
        """Parse, validate, lifecycle-admit, and queue one request.
        Raises :class:`BadRequest` (caller -> 400 / rejected record),
        :class:`QueueFull` (caller -> 503 / spool backpressure; the
        request is already recorded ``rejected``), or
        :class:`ModelUnavailable` (this feature type's breaker is open:
        HTTP -> 503 with Retry-After and a ``rejected`` record, spool ->
        defer the file untouched).

        A payload carrying ``feature_types`` (a LIST) is the multi-model
        fan-out form: one video, several models, one decode (see
        :meth:`_submit_fanout`)."""
        if isinstance(payload, dict) and "feature_types" in payload:
            return self._submit_fanout(payload, source)
        req = parse_request(payload, source)
        # the admission span covers validation + preflight probe +
        # breaker gate + queue admit; tracker.admit's request span opens
        # inside it, so the per-request trace starts at admission
        with self.telemetry.span(
            "admission", video=req.video_path, request=req.id,
            feature_type=req.feature_type, bucket=req.bucket, source=source,
        ):
            if req.feature_type not in self.scfg.feature_types:
                raise BadRequest(
                    f"feature_type {req.feature_type!r} not served (serving: "
                    f"{', '.join(self.scfg.feature_types)})"
                )
            if not os.path.exists(req.video_path):
                raise BadRequest(f"video_path does not exist: {req.video_path}")
            self._preflight(req)
            files = self._cache_lookup(req)
            if files is not None:
                # content-addressed hit: the outputs are already on disk
                # under this exact config — the request goes terminal at
                # admission, skipping queue/scheduler/chip entirely
                self.tracker.admit(req)
                return self.tracker.finish(req, "done", features=files)
            self._maybe_shed(req)
            faults.fire("admission")
            breaker = self._breaker(req.feature_type)
            if not breaker.allow_request():
                exc = ModelUnavailable(req.feature_type, breaker.retry_after_s())
                if req.source != "spool":
                    # terminal record for HTTP/local callers; the spool
                    # file is its own durable record and just waits out
                    # the open
                    self.tracker.reject(req, str(exc))
                raise exc
            self._hbm_gate(req)
            rec = self.tracker.admit(req)
            try:
                self.batcher.admit(req)
            except QueueFull:
                if self.telemetry.enabled:
                    self.telemetry.metrics.inc("requests_shed.queue_full")
                if req.source == "spool":
                    # the spool file survives and re-submits under the
                    # same id next poll: back the admit out, no terminal
                    # record
                    self.tracker.forget(req)
                else:
                    self.tracker.reject(req, f"queue full ({self.scfg.max_queue})")
                raise
            return rec

    def _preflight(self, req: ExtractionRequest) -> None:
        """Admission-time media vouching (``--preflight on``). Runs
        BEFORE the breaker gate on purpose: a corrupt upload must come
        back 422 ``invalid_media`` even while the model's breaker is
        open — it would never have reached the chip anyway. A reject
        writes the durable ``rejected`` record first (the request had an
        identity; its terminal state must survive the process), then
        raises :class:`InvalidMedia` (HTTP -> 422 body with the record,
        spool -> ``.bad`` + ``.why`` quarantine)."""
        if getattr(self.cfg, "preflight", "off") != "on":
            return
        from video_features_tpu.extract.registry import media_need_for
        from video_features_tpu.io import probe as probe_mod

        need = media_need_for(req.feature_type)
        report = probe_mod.preflight(req.video_path, need=need, caps=self._caps)
        if report.verdict != "reject":
            return
        reason = f"invalid media: {report.reason}"
        rec = self.tracker.reject(req, reason)
        raise InvalidMedia(reason, record=rec)

    # -- hit-rate-aware shedding (ISSUE 18 satellite) ---------------------

    def _maybe_shed(self, req: ExtractionRequest) -> None:
        """Saturation triage: past ``--shed_watermark`` × max_queue,
        shed requests the feature cache cannot answer. Runs AFTER
        :meth:`_cache_lookup`, so a cache hit has already gone terminal
        ``done`` and can never be shed; what reaches here is a known
        miss — the expensive kind — and shedding it keeps admission room
        for the ~ms hits. Only acts when the observed hit rate says hits
        are actually common (>= 50% over >= 20 lookups); a cold or
        miss-heavy cache sheds nothing and the plain queue bound rules."""
        wm = float(getattr(self.scfg, "shed_watermark", 0.0) or 0.0)
        if wm <= 0 or self.cache is None or not self.telemetry.enabled:
            return
        if self.batcher.depth() < wm * self.scfg.max_queue:
            return
        counters = self.telemetry.metrics.snapshot().get("counters", {})
        hits = sum(
            v for k, v in counters.items() if k.startswith("cache_hit.")
        )
        misses = sum(
            v for k, v in counters.items() if k.startswith("cache_miss.")
        )
        total = hits + misses
        if total < 20 or hits / total < 0.5:
            return
        self.telemetry.metrics.inc("requests_shed.likely_cache_miss")
        msg = (
            f"queue saturated ({self.batcher.depth()}/{self.scfg.max_queue})"
            " and this request missed the feature cache; shed to preserve"
            " admission room for cache hits"
        )
        if req.source != "spool":
            # terminal record for HTTP/local callers; a spool file is its
            # own durable record and simply retries after backoff
            self.tracker.reject(req, msg)
        raise QueueFull(msg)

    # -- HBM-aware preemption (ISSUE 18 tentpole) -------------------------

    def _headroom_bytes(self) -> Optional[int]:
        """The live ``device_mem_headroom_bytes`` gauge (min across
        devices, set by the DeviceMemorySampler), or None on backends
        without memory_stats — the preemptor then falls back to the
        static ``--hbm_budget_bytes`` arithmetic."""
        gauges = self.telemetry.metrics.snapshot().get("gauges", {})
        h = gauges.get("device_mem_headroom_bytes")
        return int(h) if h is not None else None

    def _hbm_gate(self, req: ExtractionRequest) -> None:
        """Admission HBM arbitration (only with ``--preempt on``): a
        request for a non-resident model whose ledger-projected footprint
        cannot fit beside the resident set first tries to preempt the
        lowest-value residents; only if even that cannot make room is it
        refused (503 with the cooldown as Retry-After; spool files defer
        and retry, exactly like an open breaker)."""
        if self.preemptor is None:
            return
        verdict, needed, available = self.preemptor.check(req.feature_type)
        if verdict != "overcommit":
            return
        plan = self.preemptor.ensure_room(req.feature_type)
        if plan is not None:
            # remember the sacrifice until the beneficiary's build
            # succeeds — a failed build rolls the victims back
            with self._lock:
                self._preempt_plans[req.feature_type] = plan
            return
        if self.preemptor.check(req.feature_type)[0] != "overcommit":
            return  # a concurrent admission already made room
        exc = ModelUnavailable(
            req.feature_type, self.scfg.preempt_cooldown_s,
            reason=(
                f"model {req.feature_type!r} cannot fit: needs {needed} "
                f"bytes of HBM, {available} available, and no resident "
                f"extractor is preemptible right now; retry in "
                f"{self.scfg.preempt_cooldown_s:.1f}s"
            ),
        )
        if req.source != "spool":
            self.tracker.reject(req, str(exc))
        raise exc

    def _pop_plan(self, feature_type: str) -> Optional[PreemptionPlan]:
        with self._lock:
            return self._preempt_plans.pop(feature_type, None)

    # -- multi-model fan-out ----------------------------------------------

    def _submit_fanout(self, payload: Dict[str, Any], source: str) -> Dict[str, Any]:
        """One video, several models: expand ``feature_types`` into one
        sub-request per model (ids ``<base>.<feature_type>``) and submit
        each through the normal admission path. The daemon's shared-
        decode frame cache makes the expansion decode the clip ONCE; the
        content hash is memoized, so N models hash the bytes once too.

        Sub-requests already tracked under their derived id are returned
        as-is (idempotent: a spool file re-polled after a partial
        QueueFull admits only the missing members). QueueFull and
        InvalidMedia propagate — the caller's backpressure/quarantine
        contract is per-payload; already-admitted members stay admitted
        and the duplicate tolerance absorbs the re-submit."""
        fts = payload.get("feature_types")
        if (
            not isinstance(fts, list)
            or not fts
            or not all(isinstance(f, str) and f for f in fts)
        ):
            raise BadRequest(
                "bad 'feature_types': expected a non-empty list of strings"
            )
        if "feature_type" in payload:
            raise BadRequest(
                "pass either 'feature_type' or 'feature_types', not both"
            )
        fts = list(dict.fromkeys(fts))
        unserved = [f for f in fts if f not in self.scfg.feature_types]
        if unserved:
            # validate the WHOLE list before admitting anything: a fan-out
            # must not half-run because one member named a missing model
            raise BadRequest(
                f"feature_type(s) {', '.join(map(repr, unserved))} not served "
                f"(serving: {', '.join(self.scfg.feature_types)})"
            )
        base = {k: v for k, v in payload.items() if k != "feature_types"}
        base_id = base.pop("id", None) or uuid.uuid4().hex[:12]
        subs: Dict[str, Dict[str, Any]] = {}
        for ft in fts:
            sub_id = f"{base_id}.{ft.replace('/', '-')}"
            existing = self.tracker.get(sub_id)
            if existing is not None:
                subs[ft] = existing
                continue
            sub = dict(base)
            sub["feature_type"] = ft
            sub["id"] = sub_id
            subs[ft] = self.submit(sub, source)
        states = [r.get("state") for r in subs.values()]
        return {
            "id": base_id,
            "fanout": True,
            "state": "done" if all(s == "done" for s in states) else "queued",
            "video_path": payload.get("video_path"),
            "feature_types": fts,
            "requests": subs,
        }

    # -- content-addressed cache ------------------------------------------

    def _cache_key_for(self, feature_type: str) -> tuple:
        """(config digest, feature keys, output path, on_extraction,
        output_direct) for one served model — derived from the SAME
        serving config the pool builds extractors from, WITHOUT building
        the model (admission must never pay a weights load to answer a
        lookup). Memoized: the config is immutable for the daemon's
        lifetime."""
        with self._lock:
            got = self._cache_keys.get(feature_type)
        if got is not None:
            return got
        from video_features_tpu.extract.cache import config_digest, feature_keys_for

        cfg = self.pool._serving_config(feature_type)
        out_path = (
            cfg.output_path
            if cfg.output_direct
            else os.path.join(cfg.output_path, feature_type)
        )
        got = (
            config_digest(cfg),
            feature_keys_for(cfg),
            out_path,
            cfg.on_extraction,
            cfg.output_direct,
        )
        with self._lock:
            self._cache_keys.setdefault(feature_type, got)
        return got

    def _cache_lookup(self, req: ExtractionRequest) -> Optional[List[str]]:
        """Admission-time content-addressed lookup: the materialized
        output files on a hit, None on a miss (or with caching off). Any
        cache-side failure is a miss — the normal dispatch path is
        always the fallback, never a wrong answer."""
        if self.cache is None:
            return None
        ft = req.feature_type
        try:
            chash = self.cache.content_hash(req.video_path)
        except OSError:
            return None
        digest, keys, out_path, on_ext, direct = self._cache_key_for(ft)
        cached = self.cache.lookup(chash, digest, keys)
        if cached is not None:
            try:
                files = self.cache.materialize(
                    cached,
                    self.cache.dest_files(
                        keys, req.video_path, out_path, on_ext, direct
                    ),
                )
            except OSError:
                cached = None  # payload vanished mid-copy: miss
            else:
                self.telemetry.metrics.inc(f"cache_hit.{ft}")
                return files
        self.telemetry.metrics.inc(f"cache_miss.{ft}")
        return None

    def _dispatch_group(self, key: Key, requests: List[ExtractionRequest]) -> None:
        """One coalesced group -> one resident-extractor run over the
        group's videos. Runs on the dispatcher thread; every outcome —
        including a build/dispatch crash, a watchdog timeout, or a
        breaker that opened after admission — lands as a terminal record
        on every member request.

        The group boundary is where scheduling decisions become final:
        cancel-requested members leave as ``cancelled`` and members whose
        deadline already passed leave as ``expired`` BEFORE the group
        touches the chip — an expired request must not burn compute."""
        feature_type = key[0]
        breaker: Optional[CircuitBreaker] = None
        probing = False
        resolved = False  # has the probe slot reported a verdict?
        try:
            live = self._boundary_filter(requests)
            if not live:
                return
            breaker = self._breaker(feature_type)
            probing = breaker.try_probe()
            if not probing and breaker.state() != "closed":
                # opened between admission and dispatch (or another
                # group holds the probe slot): nothing here may run
                self._shed_unavailable(live, feature_type, breaker)
                return
            try:
                ext = self.pool.get(feature_type)
                if probing:
                    # the probe group must prove the model END TO END
                    # before real traffic rides it: re-warm through the
                    # declared warmup pairs first
                    self._rewarm(ext, feature_type)
            except Exception as exc:  # noqa: BLE001 - build/re-warm failed: fail the group
                msg = f"extractor build failed: {type(exc).__name__}: {exc}"
                traceback.print_exc()
                # breaker verdict FIRST: the tracker writes below can
                # themselves raise (fault injection, full disk), and a
                # half-open probe slot claimed but never resolved would
                # wedge this model's admissions forever (ISSUE 18
                # satellite bugfix)
                if breaker.record_failure():
                    self.pool.evict(feature_type)
                resolved = True
                plan = self._pop_plan(feature_type)
                if plan is not None and self.preemptor is not None:
                    # this build was a preemption's beneficiary: hand the
                    # victims their slots back rather than serving neither
                    self.preemptor.rollback(plan)
                for r in live:
                    self.tracker.finish(
                        r, "failed", error_class=faults.classify_error(exc),
                        error_type=type(exc).__name__, message=msg,
                    )
                return
            self._pop_plan(feature_type)  # built: the preemption held up
            for r in live:
                self.tracker.dispatched(r, group_size=len(live))
            # module-level telemetry hooks (decode frame counters, bucket
            # notes) follow the extractor whose group is on the chip now
            telemetry_mod.set_current(ext.telemetry)

            def body() -> None:
                faults.fire("serve_dispatch")  # hang: the watchdog's prey
                faults.fire("extractor")  # error/oom: resident model death
                with ext.telemetry.span(
                    "request",
                    group_size=len(live),
                    requests=[r.id for r in live],
                    feature_type=feature_type,
                    bucket=key[1],
                ):
                    ext.run_paths([r.video_path for r in live])

            t_run = self.clock()
            try:
                self.watchdog.run(body)
            except Exception as exc:  # noqa: BLE001 - loop-level crash: fail the group
                traceback.print_exc()
                outcomes = ext.manifest.take()
                err = {
                    "error_class": faults.classify_error(exc),
                    "error_type": type(exc).__name__,
                    "message": str(exc)[:500],
                }
                for r in live:
                    got = outcomes.get(r.video_path)
                    if got is not None and got["status"] == "done":
                        self._finish_done(r, ext)
                    else:
                        self.tracker.finish(r, "failed", **err)
                # group-level failure: one breaker tick — UNLESS the
                # crash is input-classified (corrupt media, resource
                # caps). Hostile inputs fail their own requests but must
                # not accumulate toward opening a healthy model's
                # breaker: N corrupt uploads in a row is traffic, not an
                # infra incident. A timed-out worker is abandoned, so
                # its extractor must never be reused even if the
                # breaker stays closed.
                if faults.is_input_error(exc):
                    breaker.record_ignored()
                elif breaker.record_failure() or isinstance(exc, GroupTimeout):
                    self.pool.evict(feature_type)
                resolved = True
                return
            breaker.record_success()
            resolved = True
            if probing:
                # durable recovery trail: the re-warmed model just proved
                # itself end to end (pairs with the 'preempted' event
                # when the open was a preemption trip)
                self.tracker.manifest.event(
                    "rewarmed", feature_type=feature_type
                )
            # feed the online service-time estimator and the per-
            # (feature_type, bucket) /metrics histogram from the group
            # that just completed: the cost model only ever learns from
            # successful dispatches (crashes/timeouts are supervision
            # events, not service-time samples)
            group_s = max(self.clock() - t_run, 0.0)
            self.cost_model.observe(feature_type, key[1], len(live), group_s)
            if self.telemetry.enabled:
                self.telemetry.metrics.observe(
                    group_service_metric(feature_type, key[1]), group_s
                )
            outcomes = ext.manifest.take()
            for r in live:
                got = outcomes.get(r.video_path)
                if got is None:
                    self.tracker.finish(
                        r, "failed", error_class="permanent",
                        message="no terminal manifest record for this video",
                    )
                elif got["status"] == "done":
                    self._finish_done(r, ext)
                else:
                    self.tracker.finish(
                        r, "failed",
                        error_class=got.get("error_class"),
                        error_type=got.get("error_type"),
                        message=got.get("message"),
                    )
        finally:
            if probing and not resolved and breaker is not None:
                # safety net for any exception that escaped between
                # try_probe() and the breaker verdict: release the
                # half-open probe slot WITHOUT a verdict so the next
                # admitted group re-probes — a leaked slot would 503
                # this model until restart
                breaker.record_ignored()
            with self._lock:
                self._cancel_pending.difference_update(r.id for r in requests)

    def _boundary_filter(
        self, requests: List[ExtractionRequest]
    ) -> List[ExtractionRequest]:
        """The pre-dispatch sweep: cancel-requested members -> cancelled,
        past-deadline members -> expired; the rest run."""
        now = self.clock()
        with self._lock:
            pending = set(self._cancel_pending)
        live: List[ExtractionRequest] = []
        for r in requests:
            if r.id in pending:
                self.tracker.finish(
                    r, "cancelled", error_class="cancelled",
                    message="cancelled before dispatch",
                )
            elif r.deadline_at is not None and now > r.deadline_at:
                self.tracker.finish(
                    r, "expired", error_class="expired",
                    message=f"deadline_ms={r.deadline_ms:g} passed "
                            f"{now - r.deadline_at:.3f}s before dispatch",
                )
            else:
                live.append(r)
        return live

    def _shed_unavailable(
        self,
        requests: List[ExtractionRequest],
        feature_type: str,
        breaker: CircuitBreaker,
    ) -> None:
        """The breaker opened after these requests were admitted: spool
        requests go back to their durable home, others fail transient."""
        retry = breaker.retry_after_s()
        for r in requests:
            if r.source == "spool" and self.scfg.spool_dir:
                self.tracker.requeue(r, self.scfg.spool_dir)
            else:
                self.tracker.finish(
                    r, "failed", error_class="transient",
                    message=f"model {feature_type!r} unavailable (circuit "
                            f"breaker open); retry in {retry:.1f}s",
                )

    def _rewarm(self, ext: Any, feature_type: str) -> None:
        """Half-open probe preflight: drive this feature type's declared
        ``--warmup`` pairs through the rebuilt extractor so the probe
        proves weights + executables, not just construction. No declared
        pairs -> the probe group itself is the only proof (still end to
        end). Raises when any warm clip fails."""
        from video_features_tpu.utils.synth import synth_video

        pairs = [p for p in self.scfg.warmup_pairs() if p[0] == feature_type]
        if not pairs:
            return
        wdir = os.path.join(self.cfg.output_path, "_warmup")
        os.makedirs(wdir, exist_ok=True)
        paths: List[str] = []
        for i, (_ft, w, h) in enumerate(pairs):
            clip = os.path.join(wdir, f"warm-{w}x{h}.mp4")
            if not os.path.exists(clip):
                synth_video(clip, n_frames=8, width=w, height=h, seed=i)
            paths.append(clip)
        ext.run_paths(paths)
        outcomes = ext.manifest.take()
        bad = [p for p in paths
               if outcomes.get(p, {}).get("status") != "done"]
        if bad:
            raise RuntimeError(
                f"probe re-warm failed for {len(bad)}/{len(paths)} clip(s)"
            )

    def cancel(self, request_id: str) -> Optional[Dict[str, Any]]:
        """DELETE /v1/requests/<id> (and spool ``.cancel`` files): a
        still-queued request leaves the queue as terminal ``cancelled``;
        a dispatched one is marked cancel-requested (honored at the next
        group boundary it is still queued at — extraction already on the
        chip is never interrupted). Returns the record (with
        ``cancel_requested`` set when not yet terminal), or None for an
        unknown id."""
        rec = self.tracker.get(request_id)
        if rec is None:
            return None
        if rec.get("state") in TERMINAL_STATES:
            return rec
        req = self.batcher.cancel(request_id)
        if req is not None:
            return self.tracker.finish(
                req, "cancelled", error_class="cancelled",
                message="cancelled while queued",
            )
        with self._lock:
            self._cancel_pending.add(request_id)
        # the dispatcher may have finished it between our two looks; the
        # boundary sweep discards stale ids, so only re-read the record
        rec = self.tracker.get(request_id) or {"id": request_id}
        if rec.get("state") in TERMINAL_STATES:
            with self._lock:
                self._cancel_pending.discard(request_id)
            return rec
        out = dict(rec)
        out["cancel_requested"] = True
        return out

    def _finish_done(self, req: ExtractionRequest, ext: Any) -> None:
        files = expected_output_files(
            ext.feature_keys(),
            req.video_path,
            ext.output_path,
            ext.config.on_extraction,
            ext.config.output_direct,
        )
        self.tracker.finish(req, "done", features=[f for f in files if os.path.exists(f)])

    # -- warmup preflight -------------------------------------------------

    def warmup(self, pairs: Optional[Sequence[Tuple[str, int, int]]] = None) -> List[Dict[str, Any]]:
        """Pre-build the fused executables for the declared
        (feature_type, WxH) pairs before accepting traffic: synthesize a
        short clip at exactly that resolution and run it through the
        normal dispatch path. With ``--compile_cache`` this is a cache
        populate/hit, so a daemon restart warms in seconds; without it,
        it moves the cold compile off the first user request. Returns
        the warmup requests' terminal records."""
        from video_features_tpu.utils.synth import synth_video

        pairs = list(pairs if pairs is not None else self.scfg.warmup_pairs())
        out: List[Dict[str, Any]] = []
        wdir = os.path.join(self.cfg.output_path, "_warmup")
        os.makedirs(wdir, exist_ok=True)
        for i, (ft, w, h) in enumerate(pairs):
            clip = os.path.join(wdir, f"warm-{w}x{h}.mp4")
            if not os.path.exists(clip):
                synth_video(clip, n_frames=8, width=w, height=h, seed=i)
            req = ExtractionRequest(
                feature_type=ft, video_path=clip,
                bucket=f"{w}x{h}", source="warmup",
                id=f"warmup-{ft.replace('/', '-')}-{w}x{h}",
            )
            self.tracker.admit(req)
            self._dispatch_group(req.key(), [req])
            rec = self.tracker.get(req.id) or {}
            out.append(rec)
            print(
                f"serve: warmup {ft} {w}x{h}: {rec.get('state', '?')}"
                + (f" ({rec.get('message')})" if rec.get("state") == "failed" else "")
                + f" hbm={self._warmup_hbm(ft)}"
            )
        self._check_hbm_budget()
        return out

    def _warmup_hbm(self, feature_type: str) -> str:
        """The ledger's projected resident HBM for one model, for the
        warmup line — 'n/a' when the ledger has no HBM-platform entries
        for it (CPU backends record flops only)."""
        proj = self.ledger.hbm_projection().get(feature_type)
        return format_bytes(proj["resident"]) if proj else "n/a"

    def _check_hbm_budget(self) -> None:
        """Fail warmup fast when the projected resident set for ALL the
        resident models exceeds --hbm_budget_bytes (0 = unlimited)."""
        budget = int(self.scfg.hbm_budget_bytes or 0)
        if budget <= 0:
            return
        projected = self.ledger.projected_resident_bytes(self.scfg.feature_types)
        if projected > budget:
            raise RuntimeError(
                f"serve: projected resident HBM {format_bytes(projected)} "
                f"exceeds --hbm_budget_bytes {format_bytes(budget)} for "
                f"models {', '.join(self.scfg.feature_types)} — shrink the "
                "resident set or raise the budget"
            )

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> None:
        """Warmup (if declared), then open the request sources."""
        with self._lock:
            if self._started:
                return
            self._started = True
        if self.scfg.warmup:
            self.warmup()
        self.sampler.start()
        self.batcher.start()
        if self.scfg.retention_sweep_s > 0:
            self._sweep_thread = threading.Thread(
                target=self._sweep_loop, name="serve-retention", daemon=True
            )
            self._sweep_thread.start()
        if self.scfg.spool_dir is not None:
            from video_features_tpu.serve.sources import SpoolWatcher

            self._spool = SpoolWatcher(
                self, self.scfg.spool_dir, poll_s=self.scfg.spool_poll_s,
                replica_id=self.replica_id,
                lease_timeout_s=self.scfg.lease_timeout_s,
                registry=self.registry,
            )
            self._spool.start()
        if self.scfg.port is not None:
            from video_features_tpu.serve.server import start_http_server

            self._http_server, self._http_thread = start_http_server(
                self, self.scfg.host, self.scfg.port
            )
            host, port = self._http_server.server_address[:2]
            print(f"serve: listening on http://{host}:{port} "
                  f"(models: {', '.join(self.scfg.feature_types)})")

    @property
    def http_port(self) -> Optional[int]:
        return self._http_server.server_address[1] if self._http_server else None

    def _sweep_loop(self) -> None:
        while not self._sweep_stop.wait(self.scfg.retention_sweep_s):
            try:
                self.tracker.sweep(
                    self.scfg.request_ttl_s, self.scfg.max_request_records
                )
                self._fleet_sweep()
            except Exception:  # noqa: BLE001 - retention must not kill serving
                traceback.print_exc()

    def _fleet_sweep(self) -> None:
        """The survivors' side of fleet recovery (ISSUE 18): refresh our
        own heartbeat, export a ``replica_up`` gauge per known replica,
        and disposition requests whose owning replica is dead —
        requeue/fail via reconcile, restricted to replica-attributed
        records (``require_replica``) so a live-but-unattributed request
        is never declared a casualty mid-flight."""
        if self.scfg.lease_timeout_s <= 0:
            return
        self.registry.beat()
        timeout = self.scfg.lease_timeout_s
        ages = self.registry.ages()
        if self.telemetry.enabled:
            for rid, age in ages.items():
                self.telemetry.metrics.set_gauge(
                    f"replica_up.{rid}", 1 if age <= timeout else 0
                )
        live = {rid for rid, age in ages.items() if age <= timeout}
        live.add(self.replica_id)  # we are provably alive
        recovered = self.tracker.reconcile(
            self.scfg.spool_dir, live_replicas=live, require_replica=True
        )
        if any(recovered.values()):
            print(f"serve: fleet sweep reclaimed a dead replica's work: "
                  f"{recovered['requeued']} requeued, "
                  f"{recovered['interrupted']} interrupted")

    def status(self) -> Dict[str, Any]:
        """The /healthz body: queue depth, per-state request counts,
        which models are warm, and every circuit breaker's state (a
        breaker exists once its model has seen traffic)."""
        with self._lock:
            breakers = {ft: b.snapshot() for ft, b in sorted(self._breakers.items())}
        degraded = any(b["state"] != "closed" for b in breakers.values())
        out = {
            "status": "degraded" if degraded else "ok",
            "queue_depth": self.batcher.depth(),
            "max_queue": self.scfg.max_queue,
            "requests": self.tracker.counts(),
            "serving": list(self.scfg.feature_types),
            "warm": self.pool.feature_types(),
            "scheduler": self.scfg.scheduler,
            "breakers": breakers,
            "watchdog_timeouts": self.watchdog.timeouts(),
            "replica": self.replica_id,
        }
        if self.preemptor is not None:
            out["preemptor"] = self.preemptor.snapshot()
        return out

    def stats(self) -> Dict[str, Any]:
        """The /v1/stats body: /healthz plus the SLO window digest, the
        cost model's learned per-item service times, and the raw metrics
        snapshot — the JSON twin of /metrics."""
        out = self.status()
        out["uptime_s"] = round(max(self.clock() - self._start_mono, 0.0), 3)
        out["slo"] = self.slo.snapshot()
        out["cost_model"] = self.cost_model.snapshot()
        out["metrics"] = self.telemetry.metrics.snapshot()
        out["ledger"] = self.ledger.snapshot()
        hits, misses = self._cache_counts(out["metrics"])
        out["cache"] = {
            "enabled": self.cache is not None,
            "hits": hits,
            "misses": misses,
            "hit_rate": round(hits / (hits + misses), 4) if hits + misses else 0.0,
        }
        if self._frame_cache is not None:
            out["cache"]["frame_cache"] = self._frame_cache.stats()
        return out

    @staticmethod
    def _cache_counts(snapshot: Dict[str, Any]) -> Tuple[int, int]:
        """(hits, misses) summed over feature types from a metrics
        snapshot's ``cache_hit.<ft>`` / ``cache_miss.<ft>`` counters."""
        counters = snapshot.get("counters", {})
        hits = int(sum(
            v for k, v in counters.items() if k.startswith("cache_hit.")
        ))
        misses = int(sum(
            v for k, v in counters.items() if k.startswith("cache_miss.")
        ))
        return hits, misses

    def metrics_text(self) -> str:
        """The /metrics body: Prometheus text exposition (format 0.0.4)
        of the registry snapshot (request counters, queue gauges, stage
        and group service-time histograms) plus the serve-native
        families rendered directly from live daemon state (breakers,
        SLO quantiles, uptime, watchdog)."""
        fams = families_from_snapshot(self.telemetry.metrics.snapshot())
        fams.extend(families_from_ledger(self.ledger.snapshot()))
        fams.extend(self._serve_families())
        return render_families(fams)

    _BREAKER_STATE_CODE = {"closed": 0, "half-open": 1, "half_open": 1, "open": 2}

    def _serve_families(self) -> List[Family]:
        """Exposition families computed from live state rather than the
        metrics registry: circuit breakers, the rolling SLO window, and
        daemon uptime."""
        with self._lock:
            breakers = {ft: b.snapshot() for ft, b in sorted(self._breakers.items())}
        f_state = Family(
            "vft_breaker_state", "gauge",
            "Circuit breaker state per feature type (0=closed 1=half-open 2=open).",
        )
        f_opens = Family(
            "vft_breaker_opens_total", "counter",
            "Times each feature type's circuit breaker has opened.",
        )
        for ft, b in breakers.items():
            labels = {"feature_type": ft}
            f_state.add(labels, self._BREAKER_STATE_CODE.get(b["state"], 2))
            f_opens.add(labels, b.get("opens", 0))
        f_lat = Family(
            "vft_slo_latency_seconds", "gauge",
            "Rolling-window end-to-end request latency quantiles per priority tier.",
        )
        f_wait = Family(
            "vft_slo_queue_wait_seconds", "gauge",
            "Rolling-window queue-wait quantiles per priority tier.",
        )
        f_miss = Family(
            "vft_slo_deadline_miss_ratio", "gauge",
            "Rolling-window deadline-miss rate per priority tier "
            "(denominator: done/failed/expired requests).",
        )
        f_n = Family(
            "vft_slo_window_requests", "gauge",
            "Terminal requests inside the rolling SLO window per priority tier.",
        )
        slo = self.slo.snapshot()
        digests = {"overall": slo["overall"], **slo["tiers"]}
        quantiles = {"p50": "0.5", "p95": "0.95", "p99": "0.99"}
        for tier, d in sorted(digests.items()):
            for q, qlabel in quantiles.items():
                ql = {"tier": tier, "quantile": qlabel}
                f_lat.add(ql, d["latency_s"][q])
                f_wait.add(ql, d["queue_wait_s"][q])
            f_miss.add({"tier": tier}, d["miss_rate"])
            f_n.add({"tier": tier}, d["count"])
        f_up = Family("vft_uptime_seconds", "gauge",
                      "Seconds since the serve daemon constructed.")
        f_up.add(None, max(self.clock() - self._start_mono, 0.0))
        f_wd = Family("vft_watchdog_timeouts_total", "counter",
                      "Dispatch groups abandoned by the group watchdog.")
        f_wd.add(None, self.watchdog.timeouts())
        return [f_state, f_opens, f_lat, f_wait, f_miss, f_n, f_up, f_wd]

    def _heartbeat_line(self) -> str:
        """The serve heartbeat (replaces the batch videos/s line): queue
        depth + oldest wait, inflight groups, completion rate since the
        last beat, rolling deadline-miss rate, and any non-closed
        breakers. Runs on the telemetry drain thread."""
        now = self.clock()
        snap = self.telemetry.metrics.snapshot()
        completed = int(sum(
            snap["counters"].get(f"requests_{s}", 0)
            for s in ("done", "failed", "expired", "cancelled", "rejected")
        ))
        prev_t, prev_n = self._hb_prev
        self._hb_prev = (now, completed)
        rate = (completed - prev_n) / max(now - prev_t, 1e-9)
        inflight = int(snap["gauges"].get("groups_inflight", 0))
        with self._lock:
            open_breakers = sorted(
                ft for ft, b in self._breakers.items()
                if b.snapshot()["state"] != "closed"
            )
        line = (
            f"serve: queue={self.batcher.depth()} "
            f"oldest_wait={self.batcher.oldest_wait_s():.1f}s "
            f"inflight={inflight} completed/s={rate:.2f} "
            f"miss_rate={self.slo.miss_rate():.1%}"
        )
        if self.cache is not None:
            hits, misses = self._cache_counts(snap)
            total = hits + misses
            line += (
                f" cache_hit_rate={hits / total:.1%}" if total
                else " cache_hit_rate=n/a"
            )
        if open_breakers:
            line += " breakers_open=" + ",".join(open_breakers)
        headroom = snap["gauges"].get("device_mem_headroom_bytes")
        if headroom is not None:
            line += f" hbm_headroom={format_bytes(int(headroom))}"
        return line

    def shutdown(self, drain: bool = True) -> None:
        """Stop sources, drain (default) or durably disposition the
        backlog, close telemetry, and write the final summary.json.
        ``drain=False`` must still leave every undispatched request with
        a durable record: spool requests go back to the spool (the next
        daemon re-admits them under the same id), others are ``failed``
        interrupted — never silently stranded."""
        if self._http_server is not None:
            self._http_server.shutdown()
            self._http_server.server_close()
            if self._http_thread is not None:
                self._http_thread.join()
            self._http_server = None
            self._http_thread = None
        if self._spool is not None:
            self._spool.stop()
            self._spool = None
        if self._sweep_thread is not None:
            self._sweep_stop.set()
            self._sweep_thread.join()
            self._sweep_thread = None
        self.sampler.stop()  # idempotent; no-op when start() never ran
        for req in self.batcher.close(drain=drain):
            if req.source == "spool" and self.scfg.spool_dir:
                self.tracker.requeue(req, self.scfg.spool_dir)
            else:
                self.tracker.finish(
                    req, "failed", error_class="interrupted",
                    message="daemon shutdown before dispatch; resubmit to retry",
                )
        self.pool.close()
        # clean exit: drop the heartbeat so surviving replicas reclaim
        # anything we still lease immediately, not after a lease timeout
        self.registry.retire()
        if self._frame_cache is not None:
            # uninstall the shared-decode hook: a later daemon (or batch
            # run) in this process must not replay this daemon's frames
            from video_features_tpu.io.video import set_frame_cache

            set_frame_cache(None)
            self._frame_cache = None
        try:
            # persist the learned service times next to the compile
            # cache so the next daemon's edf-cost scheduler starts warm
            self.cost_model.save()
        except OSError:
            pass
        self.telemetry.close()
        try:
            # two summaries: per-video extraction records (the pooled
            # extractors' manifest under <output>/_manifest) and the
            # per-request lifecycle records (<output>/_requests/_manifest)
            summary = faults.finalize_run(self.cfg.output_path)
            if summary is not None:
                print(faults.format_summary(summary))
            req_summary = faults.finalize_run(self.tracker.results_dir)
            if req_summary is not None:
                print("requests: " + faults.format_summary(req_summary))
        except Exception:  # noqa: BLE001 - shutdown must finish
            traceback.print_exc()


def serve_main(argv: Optional[Sequence[str]] = None) -> None:
    """``video-features-tpu serve [warmup] ...`` — parse, build, run.

    ``serve warmup`` runs the preflight against ``--compile_cache`` and
    exits (the deploy-time "bake the cache" step); plain ``serve`` warms
    (if ``--warmup`` pairs are declared) and then serves until SIGINT.
    """
    from video_features_tpu.config import enable_compile_cache, parse_serve_args

    scfg = parse_serve_args(argv)
    enable_compile_cache(scfg.extraction)
    daemon = ServeDaemon(scfg)
    if scfg.warmup_only:
        results = daemon.warmup()
        daemon.shutdown()
        failed = [r for r in results if r.get("state") != "done"]
        if failed:
            raise SystemExit(f"serve warmup: {len(failed)}/{len(results)} pair(s) failed")
        return
    daemon.start()
    run_until_signalled(daemon)


def run_until_signalled(daemon: ServeDaemon) -> None:
    """Serve until SIGTERM / SIGINT, then drain and shut down.

    SIGTERM used to kill the process mid-flight: only KeyboardInterrupt
    reached the old ``finally``, so ``kill <pid>`` (every process
    supervisor's stop signal) lost the final telemetry flush, the
    request summary, and the cost-model save. Both signals now funnel
    into one Event and :meth:`ServeDaemon.shutdown` runs in a
    ``finally``. Handler installation is guarded so tests can call this
    off the main thread (where ``signal.signal`` raises ValueError) and
    deliver the signal themselves."""
    stop = threading.Event()

    def _handler(signum: int, frame: Any) -> None:
        print(f"serve: received signal {signum}; draining and shutting down")
        stop.set()

    installed: List[Tuple[int, Any]] = []
    for sig in (signal.SIGTERM, signal.SIGINT):
        try:
            installed.append((sig, signal.signal(sig, _handler)))
        except ValueError:
            pass
    try:
        stop.wait()
    except KeyboardInterrupt:
        print("serve: interrupted; draining and shutting down")
    finally:
        for sig, prev in installed:
            try:
                signal.signal(sig, prev)
            except ValueError:
                pass
        daemon.shutdown()
