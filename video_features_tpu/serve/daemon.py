"""The long-lived extraction daemon: resident models, warm executables,
request sources, and the ``serve`` CLI entry.

Pieces (each its own module, wired here):

- :class:`ExtractorPool` — one resident ``BaseExtractor`` per served
  feature type, built lazily and kept for the daemon's lifetime: weights
  load once, the per-bucket fused executables and ``--compile_cache``
  entries stay warm, and every group dispatch rides the existing
  ``extract/base.py`` group path (device preprocess, graceful
  degradation, classified retries — all per request, for free).
- :class:`~video_features_tpu.serve.batcher.AdmissionController` — the
  bucket-keyed coalescing queue (bounded; the backpressure contract).
- :class:`~video_features_tpu.serve.lifecycle.RequestTracker` — the
  manifest-backed queued/dispatched/done|failed record per request.
- sources — HTTP (:mod:`.server`) and the spool directory
  (:mod:`.sources`), both funneling into :meth:`ServeDaemon.submit`.

``serve warmup`` (or ``--warmup`` with traffic) pre-builds the fused
executables for declared (feature_type, WxH bucket) pairs by driving a
synthetic clip of exactly that resolution through the normal dispatch
path — against ``--compile_cache`` the daemon's first real requests then
never eat a compile, and RecompileWatch warnings (armed per extractor
under ``--preprocess device``) land in the daemon's manifest log.
"""

from __future__ import annotations

import os
import threading
import traceback
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

from video_features_tpu.config import (
    ExtractionConfig,
    ServeConfig,
    sanity_check,
)
from video_features_tpu.extract.registry import build_extractor
from video_features_tpu.io.sink import expected_output_files
from video_features_tpu.runtime import faults
from video_features_tpu.runtime import telemetry as telemetry_mod
from video_features_tpu.runtime.telemetry import Telemetry
from video_features_tpu.serve.batcher import AdmissionController, Key, QueueFull
from video_features_tpu.serve.lifecycle import (
    BadRequest,
    ExtractionRequest,
    RequestTracker,
    parse_request,
)


class _OutcomeTee:
    """Wraps an extractor's manifest: every record still reaches the real
    per-video manifest; terminal per-video records (done/failed) are
    additionally captured so the dispatcher can map them back to the
    requests of the group it just ran. Lock-guarded — records arrive
    from decode workers and the dispatcher thread alike."""

    def __init__(self, inner: Any) -> None:
        self._inner = inner
        self._lock = threading.Lock()
        self._outcomes: Dict[str, Dict[str, Any]] = {}

    @property
    def path(self):  # cli.py probes manifest.path before finalizing
        return self._inner.path

    @property
    def output_root(self):
        return self._inner.output_root

    def record(self, video: Any, status: str, **kw: Any) -> None:
        self._inner.record(video, status, **kw)
        if status in ("done", "failed"):
            with self._lock:
                self._outcomes[str(video)] = {"status": status, **kw}

    def event(self, name: str, **fields: Any) -> None:
        self._inner.event(name, **fields)

    def take(self) -> Dict[str, Dict[str, Any]]:
        """Drain the outcomes captured since the last call (the
        dispatcher calls this once per group, on its own thread)."""
        with self._lock:
            out, self._outcomes = self._outcomes, {}
        return out


class ExtractorPool:
    """Resident extractors, one per feature type, built once and reused
    for every subsequent request — the warm state a daemon exists to
    keep (no process startup, no weight reload, no re-jit)."""

    def __init__(
        self,
        cfg: ExtractionConfig,
        max_group_size: int,
        build: Callable[..., Any] = build_extractor,
    ) -> None:
        self._cfg = cfg
        self._max_group_size = max(int(max_group_size), 1)
        self._build = build
        self._lock = threading.Lock()
        self._extractors: Dict[str, Any] = {}
        self.build_count: Dict[str, int] = {}

    def _serving_config(self, feature_type: str) -> ExtractionConfig:
        """The per-feature-type extraction config: the daemon's base
        flags with the serve invariants pinned (save outputs, no resume
        probing, group size = the admission group bound, and at least
        one decode worker so the fused group path is reachable)."""
        cfg = self._cfg.replace(
            feature_type=feature_type,
            video_paths=[],
            flow_paths=None,
            file_with_video_paths=None,
            video_dir=None,
            flow_dir=None,
            on_extraction=(
                self._cfg.on_extraction
                if self._cfg.on_extraction in ("save_numpy", "save_pickle")
                else "save_numpy"
            ),
            video_batch=self._max_group_size,
            decode_workers=max(int(self._cfg.decode_workers or 0), 1),
            resume=False,
            retry_failed=False,
            strict=False,
            show_pred=False,
        )
        return sanity_check(cfg)

    def get(self, feature_type: str) -> Any:
        ext = self._extractors.get(feature_type)
        if ext is None:
            with self._lock:
                ext = self._extractors.get(feature_type)
                if ext is None:
                    ext = self._build(self._serving_config(feature_type))
                    ext.manifest = _OutcomeTee(ext.manifest)
                    self._extractors[feature_type] = ext
                    self.build_count[feature_type] = (
                        self.build_count.get(feature_type, 0) + 1
                    )
        return ext

    def feature_types(self) -> List[str]:
        with self._lock:
            return sorted(self._extractors)

    def close(self) -> None:
        with self._lock:
            exts = list(self._extractors.values())
        for ext in exts:
            try:
                ext.telemetry.close()
            except Exception:  # noqa: BLE001 - shutdown must finish
                pass


class ServeDaemon:
    """The daemon: glue between sources, admission, the pool, and the
    request tracker. Construct, :meth:`start`, then :meth:`shutdown`
    (drains by default)."""

    def __init__(self, scfg: ServeConfig, build: Callable[..., Any] = build_extractor) -> None:
        self.scfg = scfg
        self.cfg = scfg.extraction
        os.makedirs(self.cfg.output_path, exist_ok=True)
        # the daemon's own telemetry: request spans, admission gauge,
        # request counters, and the heartbeat line (which now reports
        # live queue depth — see Telemetry.heartbeat_line)
        self.telemetry = Telemetry(
            output_root=self.cfg.output_path,
            enabled=self.cfg.telemetry != "off",
            heartbeat_s=float(self.cfg.heartbeat_s or 0.0),
        )
        self.tracker = RequestTracker(self.cfg.output_path, telemetry=self.telemetry)
        self.pool = ExtractorPool(self.cfg, scfg.max_group_size, build=build)
        self.batcher = AdmissionController(
            dispatch=self._dispatch_group,
            max_group_size=scfg.max_group_size,
            max_batch_wait_s=scfg.max_batch_wait_ms / 1000.0,
            max_queue=scfg.max_queue,
            metrics=self.telemetry.metrics,
        )
        self._http_server: Any = None
        self._http_thread: Any = None
        self._spool: Any = None
        self._lock = threading.Lock()
        self._started = False

    # -- the request path ------------------------------------------------

    def submit(self, payload: Dict[str, Any], source: str) -> Dict[str, Any]:
        """Parse, validate, lifecycle-admit, and queue one request.
        Raises :class:`BadRequest` (caller -> 400 / rejected record) or
        :class:`QueueFull` (caller -> 503 / spool backpressure); on
        QueueFull the request is already recorded ``rejected``."""
        req = parse_request(payload, source)
        if req.feature_type not in self.scfg.feature_types:
            raise BadRequest(
                f"feature_type {req.feature_type!r} not served (serving: "
                f"{', '.join(self.scfg.feature_types)})"
            )
        if not os.path.exists(req.video_path):
            raise BadRequest(f"video_path does not exist: {req.video_path}")
        rec = self.tracker.admit(req)
        try:
            self.batcher.admit(req)
        except QueueFull:
            if req.source == "spool":
                # the spool file survives and re-submits under the same
                # id next poll: back the admit out, no terminal record
                self.tracker.forget(req)
            else:
                self.tracker.reject(req, f"queue full ({self.scfg.max_queue})")
            raise
        return rec

    def _dispatch_group(self, key: Key, requests: List[ExtractionRequest]) -> None:
        """One coalesced group -> one resident-extractor run over the
        group's videos. Runs on the dispatcher thread; every outcome —
        including a build/dispatch crash — lands as a terminal record on
        every member request."""
        feature_type = key[0]
        try:
            ext = self.pool.get(feature_type)
        except Exception as exc:  # noqa: BLE001 - model build failed: fail the group
            msg = f"extractor build failed: {type(exc).__name__}: {exc}"
            traceback.print_exc()
            for r in requests:
                self.tracker.finish(
                    r, "failed", error_class=faults.classify_error(exc),
                    error_type=type(exc).__name__, message=msg,
                )
            return
        for r in requests:
            self.tracker.dispatched(r, group_size=len(requests))
        # module-level telemetry hooks (decode frame counters, bucket
        # notes) follow the extractor whose group is on the chip now
        telemetry_mod.set_current(ext.telemetry)
        try:
            with ext.telemetry.span(
                "request",
                group_size=len(requests),
                requests=[r.id for r in requests],
                feature_type=feature_type,
                bucket=key[1],
            ):
                ext.run_paths([r.video_path for r in requests])
        except Exception as exc:  # noqa: BLE001 - loop-level crash: fail the group
            traceback.print_exc()
            outcomes = ext.manifest.take()
            err = {
                "error_class": faults.classify_error(exc),
                "error_type": type(exc).__name__,
                "message": str(exc)[:500],
            }
            for r in requests:
                got = outcomes.get(r.video_path)
                if got is not None and got["status"] == "done":
                    self._finish_done(r, ext)
                else:
                    self.tracker.finish(r, "failed", **err)
            return
        outcomes = ext.manifest.take()
        for r in requests:
            got = outcomes.get(r.video_path)
            if got is None:
                self.tracker.finish(
                    r, "failed", error_class="permanent",
                    message="no terminal manifest record for this video",
                )
            elif got["status"] == "done":
                self._finish_done(r, ext)
            else:
                self.tracker.finish(
                    r, "failed",
                    error_class=got.get("error_class"),
                    error_type=got.get("error_type"),
                    message=got.get("message"),
                )

    def _finish_done(self, req: ExtractionRequest, ext: Any) -> None:
        files = expected_output_files(
            ext.feature_keys(),
            req.video_path,
            ext.output_path,
            ext.config.on_extraction,
            ext.config.output_direct,
        )
        self.tracker.finish(req, "done", features=[f for f in files if os.path.exists(f)])

    # -- warmup preflight -------------------------------------------------

    def warmup(self, pairs: Optional[Sequence[Tuple[str, int, int]]] = None) -> List[Dict[str, Any]]:
        """Pre-build the fused executables for the declared
        (feature_type, WxH) pairs before accepting traffic: synthesize a
        short clip at exactly that resolution and run it through the
        normal dispatch path. With ``--compile_cache`` this is a cache
        populate/hit, so a daemon restart warms in seconds; without it,
        it moves the cold compile off the first user request. Returns
        the warmup requests' terminal records."""
        from video_features_tpu.utils.synth import synth_video

        pairs = list(pairs if pairs is not None else self.scfg.warmup_pairs())
        out: List[Dict[str, Any]] = []
        wdir = os.path.join(self.cfg.output_path, "_warmup")
        os.makedirs(wdir, exist_ok=True)
        for i, (ft, w, h) in enumerate(pairs):
            clip = os.path.join(wdir, f"warm-{w}x{h}.mp4")
            if not os.path.exists(clip):
                synth_video(clip, n_frames=8, width=w, height=h, seed=i)
            req = ExtractionRequest(
                feature_type=ft, video_path=clip,
                bucket=f"{w}x{h}", source="warmup",
                id=f"warmup-{ft.replace('/', '-')}-{w}x{h}",
            )
            self.tracker.admit(req)
            self._dispatch_group(req.key(), [req])
            rec = self.tracker.get(req.id) or {}
            out.append(rec)
            print(
                f"serve: warmup {ft} {w}x{h}: {rec.get('state', '?')}"
                + (f" ({rec.get('message')})" if rec.get("state") == "failed" else "")
            )
        return out

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> None:
        """Warmup (if declared), then open the request sources."""
        with self._lock:
            if self._started:
                return
            self._started = True
        if self.scfg.warmup:
            self.warmup()
        self.batcher.start()
        if self.scfg.spool_dir is not None:
            from video_features_tpu.serve.sources import SpoolWatcher

            self._spool = SpoolWatcher(
                self, self.scfg.spool_dir, poll_s=self.scfg.spool_poll_s
            )
            self._spool.start()
        if self.scfg.port is not None:
            from video_features_tpu.serve.server import start_http_server

            self._http_server, self._http_thread = start_http_server(
                self, self.scfg.host, self.scfg.port
            )
            host, port = self._http_server.server_address[:2]
            print(f"serve: listening on http://{host}:{port} "
                  f"(models: {', '.join(self.scfg.feature_types)})")

    @property
    def http_port(self) -> Optional[int]:
        return self._http_server.server_address[1] if self._http_server else None

    def status(self) -> Dict[str, Any]:
        """The /healthz body: queue depth, per-state request counts, and
        which models are warm."""
        return {
            "status": "ok",
            "queue_depth": self.batcher.depth(),
            "max_queue": self.scfg.max_queue,
            "requests": self.tracker.counts(),
            "serving": list(self.scfg.feature_types),
            "warm": self.pool.feature_types(),
        }

    def shutdown(self, drain: bool = True) -> None:
        """Stop sources, drain (default) or reject the backlog, close
        telemetry, and write the final summary.json."""
        if self._http_server is not None:
            self._http_server.shutdown()
            self._http_server.server_close()
            if self._http_thread is not None:
                self._http_thread.join()
            self._http_server = None
            self._http_thread = None
        if self._spool is not None:
            self._spool.stop()
            self._spool = None
        for req in self.batcher.close(drain=drain):
            self.tracker.reject(req, "daemon shutdown before dispatch")
        self.pool.close()
        self.telemetry.close()
        try:
            # two summaries: per-video extraction records (the pooled
            # extractors' manifest under <output>/_manifest) and the
            # per-request lifecycle records (<output>/_requests/_manifest)
            summary = faults.finalize_run(self.cfg.output_path)
            if summary is not None:
                print(faults.format_summary(summary))
            req_summary = faults.finalize_run(self.tracker.results_dir)
            if req_summary is not None:
                print("requests: " + faults.format_summary(req_summary))
        except Exception:  # noqa: BLE001 - shutdown must finish
            traceback.print_exc()


def serve_main(argv: Optional[Sequence[str]] = None) -> None:
    """``video-features-tpu serve [warmup] ...`` — parse, build, run.

    ``serve warmup`` runs the preflight against ``--compile_cache`` and
    exits (the deploy-time "bake the cache" step); plain ``serve`` warms
    (if ``--warmup`` pairs are declared) and then serves until SIGINT.
    """
    from video_features_tpu.config import enable_compile_cache, parse_serve_args

    scfg = parse_serve_args(argv)
    enable_compile_cache(scfg.extraction)
    daemon = ServeDaemon(scfg)
    if scfg.warmup_only:
        results = daemon.warmup()
        daemon.shutdown()
        failed = [r for r in results if r.get("state") != "done"]
        if failed:
            raise SystemExit(f"serve warmup: {len(failed)}/{len(results)} pair(s) failed")
        return
    daemon.start()
    try:
        threading.Event().wait()  # serve until interrupted
    except KeyboardInterrupt:
        print("serve: draining and shutting down")
    finally:
        daemon.shutdown()
