"""Spool-directory request source: the air-gapped twin of the HTTP door.

Protocol (documented in docs/serving.md): a producer writes a request as
``<spool>/<name>.json`` — atomically, via write-to-temp + rename into
the directory, exactly like the sinks in io/ — with the same schema as
the HTTP body (including the multi-model ``feature_types`` LIST form:
one decode fanned out to several models; a re-polled fan-out file only
admits the members the previous attempt could not, the rest resolve as
duplicates of already-tracked sub-requests). Scheduling hints can ride in the payload
(``priority``/``deadline_ms``) or, for producers that only control the
filename, in the name itself: ``<base>.pN.json`` sets priority N and
``<base>.dMS.json`` sets deadline_ms MS (combined: ``clip.p7.d500.json``
— payload fields win over filename hints). The watcher polls
(``--spool_poll_s``), claims a file by renaming it to
``<name>.json.claimed`` (rename is the mutual exclusion: two watchers on
one spool can race a file, only one rename wins), then submits it:

- admitted       -> claimed file is deleted; track via the result JSON
                    under ``<output>/_requests/<id>.json``
- malformed      -> renamed to ``<name>.json.bad`` with a ``.why`` file
                    (and, when the payload named an id, a rejected
                    lifecycle record) — poison files must leave the
                    scan path or they re-fail every poll
- queue full /   -> the claim is renamed BACK to ``<name>.json``: the
  breaker open      file system is the retry queue, which is the whole
                    point of a spool. The un-claimed file is then
                    *deferred* with jittered exponential backoff
                    (:func:`~video_features_tpu.runtime.faults.
                    backoff_delay`) so a full queue or an open breaker
                    never turns the poll into a tight claim/rename spin.

Cancellation: dropping ``<id>.cancel`` into the spool cancels request
``<id>`` — an unclaimed ``<id>.json`` is deleted before it is ever
admitted; otherwise the cancel routes through ``daemon.cancel`` exactly
like ``DELETE /v1/requests/<id>``. The ``.cancel`` file is consumed
once handled.
"""

from __future__ import annotations

import json
import os
import re
import threading
import time
import traceback
from typing import Any, Callable, Dict

from video_features_tpu.runtime import faults as faults_mod
from video_features_tpu.serve.batcher import QueueFull
from video_features_tpu.serve.lifecycle import BadRequest
from video_features_tpu.serve.supervisor import ModelUnavailable

# a deferred file is retried after at most this long no matter how many
# times it has been deferred — backpressure is expected to clear
MAX_DEFER_S = 30.0

# filename scheduling hints: trailing .pN / .dMS segments before .json
_NAME_HINT_RE = re.compile(r"\.(p([0-9])|d([0-9]{1,9}))$")


def parse_spool_name(name: str) -> Dict[str, Any]:
    """Extract ``priority``/``deadline_ms`` hints from a spool filename
    (without its ``.json`` suffix). Unrecognized segments are simply part
    of the request name — this never raises."""
    hints: Dict[str, Any] = {}
    base = name
    while True:
        m = _NAME_HINT_RE.search(base)
        if m is None:
            return hints
        if m.group(2) is not None:
            hints.setdefault("priority", int(m.group(2)))
        else:
            hints.setdefault("deadline_ms", float(m.group(3)))
        base = base[: m.start()]


class SpoolWatcher:
    """Polls a spool directory and feeds ``daemon.submit``. One thread;
    start()/stop(); a single :meth:`poll_once` pass is the deterministic
    unit the tests drive directly (with an injectable clock, so deferral
    backoff is tested without sleeping)."""

    def __init__(
        self,
        daemon: Any,
        spool_dir: str,
        poll_s: float = 0.5,
        clock: Callable[[], float] = time.monotonic,
    ) -> None:
        self.daemon = daemon
        self.spool_dir = spool_dir
        self.poll_s = max(float(poll_s), 0.01)
        self._clock = clock
        os.makedirs(spool_dir, exist_ok=True)
        # name -> (attempts, retry_at): files bounced by backpressure
        # (queue full / breaker open) are skipped until retry_at — the
        # jittered re-scan backoff that replaces the old tight spin
        self._deferred: Dict[str, Any] = {}
        self._stop = threading.Event()
        self._thread: threading.Thread = threading.Thread(
            target=self._loop, name="serve-spool", daemon=True
        )

    def start(self) -> None:
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread.is_alive():
            self._thread.join()

    def _loop(self) -> None:
        while not self._stop.is_set():
            try:
                self.poll_once()
            except Exception:  # noqa: BLE001 - the watcher must outlive one bad pass
                traceback.print_exc()
            self._stop.wait(self.poll_s)

    def _defer(self, name: str, path: str, claimed: str) -> None:
        """Un-claim and schedule the next attempt: exponential in this
        file's bounce count, deterministically jittered by name so a
        burst of deferred files does not re-arrive in lockstep."""
        try:
            os.replace(claimed, path)  # un-claim: spool = retry queue
        except OSError:
            pass
        attempts = int(self._deferred.get(name, (0, 0.0))[0]) + 1
        delay = min(
            faults_mod.backoff_delay(attempts, base=self.poll_s, key=name),
            MAX_DEFER_S,
        )
        self._deferred[name] = (attempts, self._clock() + delay)

    def poll_once(self) -> int:
        """One scan pass; returns how many files were admitted.
        ``.cancel`` files are handled first (a cancel racing its request
        in one scan must win); deferred files are skipped until their
        backoff expires."""
        try:
            names = sorted(os.listdir(self.spool_dir))
        except OSError:
            return 0
        now = self._clock()
        admitted = 0
        for name in names:
            if name.endswith(".cancel"):
                self._handle_cancel(name)
        for name in names:
            if not name.endswith(".json"):
                continue
            entry = self._deferred.get(name)
            if entry is not None and now < entry[1]:
                continue
            path = os.path.join(self.spool_dir, name)
            claimed = path + ".claimed"
            try:
                os.rename(path, claimed)  # the claim; losing the race is fine
            except OSError:
                continue
            try:
                with open(claimed, "r", encoding="utf-8") as fh:
                    payload = json.load(fh)
                if isinstance(payload, dict):
                    for k, v in parse_spool_name(name[: -len(".json")]).items():
                        payload.setdefault(k, v)
                self.daemon.submit(payload, source="spool")
            except QueueFull:
                self._defer(name, path, claimed)
                return admitted  # the whole queue is full: end the pass
            except ModelUnavailable:
                # one model's breaker is open; other files may still be
                # admissible, so defer this one and keep scanning
                self._defer(name, path, claimed)
            except (ValueError, BadRequest) as exc:
                self._deferred.pop(name, None)
                self._quarantine(claimed, name, exc)
            else:
                admitted += 1
                self._deferred.pop(name, None)
                os.unlink(claimed)
        return admitted

    def _handle_cancel(self, name: str) -> None:
        """``<id>.cancel``: delete the matching unclaimed ``<id>.json``
        if it is still here (cancelled before admission — terminal
        record included), else route through ``daemon.cancel``. The
        ``.cancel`` file is consumed either way."""
        rid = name[: -len(".cancel")]
        cancel_path = os.path.join(self.spool_dir, name)
        spooled = os.path.join(self.spool_dir, f"{rid}.json")
        try:
            os.unlink(spooled)
        except OSError:
            rec = self.daemon.cancel(rid)
            if rec is None:
                print(f"serve: spool cancel for unknown request {rid!r}")
        else:
            self._deferred.pop(f"{rid}.json", None)
            from video_features_tpu.serve.lifecycle import ExtractionRequest

            self.daemon.tracker.finish(
                ExtractionRequest(
                    feature_type="", video_path="", id=rid, source="spool"
                ),
                "cancelled", error_class="cancelled",
                message="cancelled in spool before admission",
            )
        try:
            os.unlink(cancel_path)
        except OSError:
            pass

    def _quarantine(self, claimed: str, name: str, exc: Exception) -> None:
        bad = os.path.join(self.spool_dir, name + ".bad")
        try:
            os.replace(claimed, bad)
            with open(bad + ".why", "w", encoding="utf-8") as fh:
                fh.write(f"{type(exc).__name__}: {exc}\n")
        except OSError:
            pass
        print(f"serve: spool file {name} rejected: {exc}")
