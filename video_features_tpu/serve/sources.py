"""Spool-directory request source: the air-gapped twin of the HTTP door.

Protocol (documented in docs/serving.md): a producer writes a request as
``<spool>/<name>.json`` — atomically, via write-to-temp + rename into
the directory, exactly like the sinks in io/ — with the same schema as
the HTTP body (including the multi-model ``feature_types`` LIST form:
one decode fanned out to several models; a re-polled fan-out file only
admits the members the previous attempt could not, the rest resolve as
duplicates of already-tracked sub-requests). Scheduling hints can ride in the payload
(``priority``/``deadline_ms``) or, for producers that only control the
filename, in the name itself: ``<base>.pN.json`` sets priority N and
``<base>.dMS.json`` sets deadline_ms MS (combined: ``clip.p7.d500.json``
— payload fields win over filename hints). The watcher polls
(``--spool_poll_s``), claims a file by renaming it to
``<name>.json.claim.<replica_id>`` (rename is the mutual exclusion: two
watchers on one spool can race a file, only one rename wins), then
submits it:

- admitted       -> claimed file is deleted; track via the result JSON
                    under ``<output>/_requests/<id>.json``
- malformed      -> renamed to ``<name>.json.bad`` with a ``.why`` file
                    (and, when the payload named an id, a rejected
                    lifecycle record) — poison files must leave the
                    scan path or they re-fail every poll
- queue full /   -> the claim is renamed BACK to ``<name>.json``: the
  breaker open      file system is the retry queue, which is the whole
                    point of a spool. The un-claimed file is then
                    *deferred* with jittered exponential backoff
                    (:func:`~video_features_tpu.runtime.faults.
                    backoff_delay`) so a full queue or an open breaker
                    never turns the poll into a tight claim/rename spin.

Cancellation: dropping ``<id>.cancel`` into the spool cancels request
``<id>`` — an unclaimed ``<id>.json`` is deleted before it is ever
admitted; otherwise the cancel routes through ``daemon.cancel`` exactly
like ``DELETE /v1/requests/<id>``. The ``.cancel`` file is consumed
once handled.

Fleet mode (ISSUE 18, ``--lease_timeout_s > 0``): the claim file is a
*lease* — it stays on disk until every request it admitted is terminal,
its mtime refreshed every poll as the heartbeat. A replica that dies
(SIGKILL — no cleanup) leaves stale leases; surviving watchers check the
owner's :class:`~video_features_tpu.serve.lifecycle.ReplicaRegistry`
heartbeat and, once both heartbeats are stale, rename the claim back to
``<name>.json`` so the request re-enters the scan path (work stealing).
Steals prefer warm replicas: a claim on a model the stealing replica
does not have resident waits ``COLD_STEAL_FACTOR`` × longer, so a peer
with the executable already warm usually wins the reclaim race.
"""

from __future__ import annotations

import json
import os
import re
import threading
import time
import traceback
from typing import Any, Callable, Dict, Optional

from video_features_tpu.runtime import faults as faults_mod
from video_features_tpu.serve.batcher import QueueFull
from video_features_tpu.serve.lifecycle import (
    TERMINAL_STATES,
    BadRequest,
    DuplicateRequest,
)
from video_features_tpu.serve.supervisor import ModelUnavailable

# a deferred file is retried after at most this long no matter how many
# times it has been deferred — backpressure is expected to clear
MAX_DEFER_S = 30.0

# a stale claim on a model this replica does NOT have warm waits this
# multiple of the lease timeout before being stolen — the affinity
# grace window in which a warm peer gets first crack at the reclaim
COLD_STEAL_FACTOR = 1.5

# filename scheduling hints: trailing .pN / .dMS segments before .json
_NAME_HINT_RE = re.compile(r"\.(p([0-9])|d([0-9]{1,9}))$")


def parse_spool_name(name: str) -> Dict[str, Any]:
    """Extract ``priority``/``deadline_ms`` hints from a spool filename
    (without its ``.json`` suffix). Unrecognized segments are simply part
    of the request name — this never raises."""
    hints: Dict[str, Any] = {}
    base = name
    while True:
        m = _NAME_HINT_RE.search(base)
        if m is None:
            return hints
        if m.group(2) is not None:
            hints.setdefault("priority", int(m.group(2)))
        else:
            hints.setdefault("deadline_ms", float(m.group(3)))
        base = base[: m.start()]


class SpoolWatcher:
    """Polls a spool directory and feeds ``daemon.submit``. One thread;
    start()/stop(); a single :meth:`poll_once` pass is the deterministic
    unit the tests drive directly (with an injectable clock, so deferral
    backoff is tested without sleeping)."""

    def __init__(
        self,
        daemon: Any,
        spool_dir: str,
        poll_s: float = 0.5,
        clock: Callable[[], float] = time.monotonic,
        replica_id: Optional[str] = None,
        lease_timeout_s: float = 0.0,
        registry: Any = None,
    ) -> None:
        self.daemon = daemon
        self.spool_dir = spool_dir
        self.poll_s = max(float(poll_s), 0.01)
        self._clock = clock
        # fleet identity (ISSUE 18): claims are per-replica lease files
        # <name>.json.claim.<replica>; lease_timeout_s > 0 turns on the
        # steal protocol (hold the claim until the request is terminal,
        # heartbeat its mtime each poll, reclaim peers' stale claims).
        # At 0 the claim is still replica-suffixed but deleted right
        # after admission — the single-replica behavior.
        self.replica_id = str(replica_id) if replica_id else f"r{os.getpid()}"
        self.lease_timeout_s = max(float(lease_timeout_s), 0.0)
        self.registry = registry  # lifecycle.ReplicaRegistry or None
        os.makedirs(spool_dir, exist_ok=True)
        # name -> (attempts, retry_at): files bounced by backpressure
        # (queue full / breaker open) are skipped until retry_at — the
        # jittered re-scan backoff that replaces the old tight spin
        self._deferred: Dict[str, Any] = {}
        # claim path -> request ids it covers; the lease is released
        # (claim unlinked) once every covered request is terminal
        self._inflight: Dict[str, Any] = {}
        self._stop = threading.Event()
        self._thread: threading.Thread = threading.Thread(
            target=self._loop, name="serve-spool", daemon=True
        )

    def start(self) -> None:
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread.is_alive():
            self._thread.join()

    def _loop(self) -> None:
        while not self._stop.is_set():
            try:
                self.poll_once()
            except Exception:  # noqa: BLE001 - the watcher must outlive one bad pass
                traceback.print_exc()
            self._stop.wait(self.poll_s)

    def _defer(self, name: str, path: str, claimed: str) -> None:
        """Un-claim and schedule the next attempt: exponential in this
        file's bounce count, deterministically jittered by name so a
        burst of deferred files does not re-arrive in lockstep."""
        try:
            os.replace(claimed, path)  # un-claim: spool = retry queue
        except OSError:
            pass
        attempts = int(self._deferred.get(name, (0, 0.0))[0]) + 1
        delay = min(
            faults_mod.backoff_delay(attempts, base=self.poll_s, key=name),
            MAX_DEFER_S,
        )
        self._deferred[name] = (attempts, self._clock() + delay)

    def poll_once(self) -> int:
        """One scan pass; returns how many files were admitted.
        ``.cancel`` files are handled first (a cancel racing its request
        in one scan must win); deferred files are skipped until their
        backoff expires; with leases on, held leases are heartbeat and
        peers' stale claims reclaimed before the scan."""
        try:
            # the chaos drill's kill point: --fault_inject
            # replica_kill:kill:N SIGKILLs this replica mid-poll (no
            # cleanup, no flush); any other kind here is a no-op
            faults_mod.fire("replica_kill")
        except Exception:  # noqa: BLE001 - only the kill kind is meaningful
            pass
        if self.registry is not None:
            self.registry.beat()
        self._lease_pass()
        try:
            names = sorted(os.listdir(self.spool_dir))
        except OSError:
            return 0
        now = self._clock()
        admitted = 0
        for name in names:
            if name.endswith(".cancel"):
                self._handle_cancel(name)
        for name in names:
            if not name.endswith(".json"):
                continue
            entry = self._deferred.get(name)
            if entry is not None and now < entry[1]:
                continue
            path = os.path.join(self.spool_dir, name)
            claimed = f"{path}.claim.{self.replica_id}"
            try:
                os.rename(path, claimed)  # the claim; losing the race is fine
            except OSError:
                continue
            try:
                with open(claimed, "r", encoding="utf-8") as fh:
                    payload = json.load(fh)
                if isinstance(payload, dict):
                    for k, v in parse_spool_name(name[: -len(".json")]).items():
                        payload.setdefault(k, v)
                rec = self.daemon.submit(payload, source="spool")
            except QueueFull:
                self._defer(name, path, claimed)
                return admitted  # the whole queue is full: end the pass
            except ModelUnavailable:
                # one model's breaker is open; other files may still be
                # admissible, so defer this one and keep scanning
                self._defer(name, path, claimed)
            except DuplicateRequest:
                # already tracked live here (lease steal / reconcile
                # requeue race): this file is the losing copy — drop it,
                # the tracked request owns the outcome
                self._deferred.pop(name, None)
                self._unlink(claimed)
            except (ValueError, BadRequest) as exc:
                self._deferred.pop(name, None)
                self._quarantine(claimed, name, exc)
            else:
                admitted += 1
                self._deferred.pop(name, None)
                if self.lease_timeout_s > 0:
                    # the claim file IS the lease: held (mtime-heartbeat)
                    # until every covered request is terminal, so a
                    # SIGKILLed replica leaves a reclaimable stale lease
                    self._inflight[claimed] = self._request_ids(rec)
                else:
                    self._unlink(claimed)
        return admitted

    # -- lease protocol (ISSUE 18) --------------------------------------

    @staticmethod
    def _request_ids(rec: Any) -> list:
        """Request ids covered by one admission record (a fan-out record
        covers one sub-request per model)."""
        if isinstance(rec, dict):
            if rec.get("fanout"):
                return [r.get("id") for r in rec.get("requests", {}).values()
                        if isinstance(r, dict) and r.get("id")]
            if rec.get("id"):
                return [rec["id"]]
        return []

    def _terminal(self, rid: str) -> bool:
        """A request unknown to the tracker counts as terminal — it was
        finished and swept by retention; holding its lease forever would
        block the file from ever being garbage-collected."""
        get = getattr(getattr(self.daemon, "tracker", None), "get", None)
        if get is None:
            return True
        rec = get(rid)
        return rec is None or rec.get("state") in TERMINAL_STATES

    def _lease_pass(self) -> None:
        """Release finished leases, heartbeat live ones, and reclaim
        peers' stale claims. ``lease_stall`` chaos stage: an injected
        raise skips THIS replica's heartbeat refresh (the replica is
        alive but wedged), so peers see its leases age out — the steal
        path is exercised without killing anyone."""
        if self.lease_timeout_s <= 0:
            return
        stalled = False
        try:
            faults_mod.fire("lease_stall")
        except Exception:  # noqa: BLE001 - any injected kind means 'stall'
            stalled = True
        for claim, rids in list(self._inflight.items()):
            if all(self._terminal(r) for r in rids):
                self._inflight.pop(claim, None)
                self._unlink(claim)
            elif not stalled:
                try:
                    os.utime(claim)
                except OSError:
                    # the claim was stolen out from under us (our own
                    # heartbeat stalled long enough): the thief owns the
                    # requests now, stop renewing
                    self._inflight.pop(claim, None)
        self._reclaim_stale()

    def _warm_feature_types(self) -> set:
        pool = getattr(self.daemon, "pool", None)
        try:
            return set(pool.feature_types()) if pool is not None else set()
        except Exception:  # noqa: BLE001 - affinity is advisory only
            return set()

    def _reclaim_stale(self) -> None:
        """Steal dead peers' claims: a ``<name>.json.claim.<other>``
        whose owner has no fresh registry heartbeat AND whose own mtime
        heartbeat is stale is renamed back to ``<name>.json``, putting
        the request back in the scan path. Affinity: a claim on a model
        this replica has warm is stolen at ``lease_timeout_s``; a cold
        one waits ``COLD_STEAL_FACTOR`` longer, giving warm peers first
        crack. mtimes are wall-clock — the one clock replicas share."""
        try:
            names = os.listdir(self.spool_dir)
        except OSError:
            return
        marker = ".json.claim."
        live = None
        if self.registry is not None:
            live = self.registry.live(self.lease_timeout_s)
        warm = self._warm_feature_types()
        now = time.time()
        for name in names:
            i = name.rfind(marker)
            if i < 0:
                continue
            owner = name[i + len(marker):]
            if not owner or owner == self.replica_id:
                continue
            if live is not None and owner in live:
                continue  # the owner replica is alive; its lease stands
            claim = os.path.join(self.spool_dir, name)
            try:
                age = now - os.stat(claim).st_mtime
            except OSError:
                continue
            threshold = self.lease_timeout_s
            ft = self._claim_feature_type(claim)
            if ft is not None and warm and ft not in warm:
                threshold *= COLD_STEAL_FACTOR
            if age <= threshold:
                continue
            original = os.path.join(self.spool_dir, name[: i + len(".json")])
            try:
                os.rename(claim, original)
            except OSError:
                continue  # a peer won the steal race; fine
            self._steal_telemetry(owner, ft, name[: i + len(".json")])

    @staticmethod
    def _claim_feature_type(claim: str) -> Optional[str]:
        try:
            with open(claim, "r", encoding="utf-8") as fh:
                payload = json.load(fh)
            if isinstance(payload, dict):
                ft = payload.get("feature_type")
                if isinstance(ft, str):
                    return ft
                fts = payload.get("feature_types")
                if isinstance(fts, list) and fts and isinstance(fts[0], str):
                    return fts[0]
        except (OSError, ValueError):
            pass
        return None

    def _steal_telemetry(self, owner: str, ft: Optional[str], name: str) -> None:
        telemetry = getattr(self.daemon, "telemetry", None)
        if telemetry is not None and getattr(telemetry, "enabled", False):
            telemetry.metrics.inc("lease_expired")
            telemetry.metrics.inc(f"lease_steals.{ft or 'unknown'}")
        manifest = getattr(getattr(self.daemon, "tracker", None), "manifest", None)
        if manifest is not None:
            manifest.event(
                "lease_stolen", file=name, from_replica=owner,
                by_replica=self.replica_id, feature_type=ft,
            )

    @staticmethod
    def _unlink(path: str) -> None:
        try:
            os.unlink(path)
        except OSError:
            pass

    def _handle_cancel(self, name: str) -> None:
        """``<id>.cancel``: delete the matching unclaimed ``<id>.json``
        if it is still here (cancelled before admission — terminal
        record included), else route through ``daemon.cancel``. The
        ``.cancel`` file is consumed either way."""
        rid = name[: -len(".cancel")]
        cancel_path = os.path.join(self.spool_dir, name)
        spooled = os.path.join(self.spool_dir, f"{rid}.json")
        try:
            os.unlink(spooled)
        except OSError:
            rec = self.daemon.cancel(rid)
            if rec is None:
                print(f"serve: spool cancel for unknown request {rid!r}")
        else:
            self._deferred.pop(f"{rid}.json", None)
            from video_features_tpu.serve.lifecycle import ExtractionRequest

            self.daemon.tracker.finish(
                ExtractionRequest(
                    feature_type="", video_path="", id=rid, source="spool"
                ),
                "cancelled", error_class="cancelled",
                message="cancelled in spool before admission",
            )
        try:
            os.unlink(cancel_path)
        except OSError:
            pass

    def _quarantine(self, claimed: str, name: str, exc: Exception) -> None:
        bad = os.path.join(self.spool_dir, name + ".bad")
        why_tmp = bad + ".why.tmp"
        try:
            os.replace(claimed, bad)
            # staged like every durable publish (GC601): the .why sidecar
            # is what an operator reads to triage, so it must never be torn
            with open(why_tmp, "w", encoding="utf-8") as fh:
                fh.write(f"{type(exc).__name__}: {exc}\n")
            os.replace(why_tmp, bad + ".why")
        except OSError:
            pass
        print(f"serve: spool file {name} rejected: {exc}")
