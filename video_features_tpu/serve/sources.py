"""Spool-directory request source: the air-gapped twin of the HTTP door.

Protocol (documented in docs/serving.md): a producer writes a request as
``<spool>/<name>.json`` — atomically, via write-to-temp + rename into
the directory, exactly like the sinks in io/ — with the same schema as
the HTTP body. The watcher polls (``--spool_poll_s``), claims a file by
renaming it to ``<name>.json.claimed`` (rename is the mutual exclusion:
two watchers on one spool can race a file, only one rename wins), then
submits it:

- admitted       -> claimed file is deleted; track via the result JSON
                    under ``<output>/_requests/<id>.json``
- malformed      -> renamed to ``<name>.json.bad`` with a ``.why`` file
                    (and, when the payload named an id, a rejected
                    lifecycle record) — poison files must leave the
                    scan path or they re-fail every poll
- queue full     -> the claim is renamed BACK to ``<name>.json``: the
                    file system is the retry queue, which is the whole
                    point of a spool; next poll retries.
"""

from __future__ import annotations

import json
import os
import threading
import traceback
from typing import Any

from video_features_tpu.serve.batcher import QueueFull
from video_features_tpu.serve.lifecycle import BadRequest


class SpoolWatcher:
    """Polls a spool directory and feeds ``daemon.submit``. One thread;
    start()/stop(); a single :meth:`poll_once` pass is the deterministic
    unit the tests drive directly."""

    def __init__(self, daemon: Any, spool_dir: str, poll_s: float = 0.5) -> None:
        self.daemon = daemon
        self.spool_dir = spool_dir
        self.poll_s = max(float(poll_s), 0.01)
        os.makedirs(spool_dir, exist_ok=True)
        self._stop = threading.Event()
        self._thread: threading.Thread = threading.Thread(
            target=self._loop, name="serve-spool", daemon=True
        )

    def start(self) -> None:
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread.is_alive():
            self._thread.join()

    def _loop(self) -> None:
        while not self._stop.is_set():
            try:
                self.poll_once()
            except Exception:  # noqa: BLE001 - the watcher must outlive one bad pass
                traceback.print_exc()
            self._stop.wait(self.poll_s)

    def poll_once(self) -> int:
        """One scan pass; returns how many files were admitted. Stops
        early on queue-full — everything left in the directory is
        naturally deferred to the next poll."""
        try:
            names = sorted(os.listdir(self.spool_dir))
        except OSError:
            return 0
        admitted = 0
        for name in names:
            if not name.endswith(".json"):
                continue
            path = os.path.join(self.spool_dir, name)
            claimed = path + ".claimed"
            try:
                os.rename(path, claimed)  # the claim; losing the race is fine
            except OSError:
                continue
            try:
                with open(claimed, "r", encoding="utf-8") as fh:
                    payload = json.load(fh)
                self.daemon.submit(payload, source="spool")
            except QueueFull:
                os.replace(claimed, path)  # un-claim: spool = retry queue
                return admitted
            except (ValueError, BadRequest) as exc:
                self._quarantine(claimed, name, exc)
            else:
                admitted += 1
                os.unlink(claimed)
        return admitted

    def _quarantine(self, claimed: str, name: str, exc: Exception) -> None:
        bad = os.path.join(self.spool_dir, name + ".bad")
        try:
            os.replace(claimed, bad)
            with open(bad + ".why", "w", encoding="utf-8") as fh:
                fh.write(f"{type(exc).__name__}: {exc}\n")
        except OSError:
            pass
        print(f"serve: spool file {name} rejected: {exc}")
