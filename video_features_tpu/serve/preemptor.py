"""HBM-aware extractor preemption: make room instead of rejecting.

The tentpole of ISSUE 18. Before it, a mixed-model burst whose
ledger-projected footprint could not fit beside the resident set got a
503 (``--hbm_budget_bytes`` warmup gate) or an OOM gamble; the cost
ledger (PR 13) could *price* every resident model but nothing acted on
the price. The :class:`Preemptor` closes that loop at admission time:

- **Fit check** (:meth:`check`): a non-resident feature type's projected
  resident bytes (``CostLedger.hbm_projection`` — arguments maxed,
  generated code summed, the PR 13 approximation) are compared against
  live headroom: the ``device_mem_headroom_bytes`` gauge when the
  sampler runs, else ``--hbm_budget_bytes`` minus the projected resident
  set. No projection for the model (CPU platform entries project
  nothing, by design) or no headroom signal → ``"unknown"``: preemption
  quietly disables itself, it never guesses and never crashes.
- **Value ranking** (:meth:`value_score`): residents are scored by
  (1 + max queued priority tier) × (1 + queued count × ServiceTimeModel
  demand EWMA) × (1 + warm executable count from the ledger) — the
  Arachne framing: the victim is the model whose eviction forfeits the
  least queued value and the least re-compile sunk cost. Ties break
  lexicographically by feature type, so equal-value ranking is stable
  across runs.
- **Teardown through the breaker** (:meth:`ensure_room`): each victim is
  evicted from the pool AND its breaker is force-opened
  (:meth:`~video_features_tpu.serve.supervisor.CircuitBreaker.trip`), so
  its traffic defers (503 / spool backoff) instead of racing a rebuild
  into the memory it just freed; the re-warm rides the normal cooldown →
  half-open → probe path, ``--compile_cache`` keeping it cheap. A
  ``preempted`` manifest event per victim and a ``rewarmed`` event when
  the probe closes the breaker make the trail durable.
- **Hysteresis**: a global ``--preempt_cooldown_s`` between preemptions
  plus a per-model min-residency guard (``--preempt_min_residency_s``
  since the victim's build) bound thrash — two bursts can trade 503s,
  they cannot trade evictions faster than the cooldown.
- **Rollback** (:meth:`rollback`): if the beneficiary's build fails, the
  plan's victims get their breakers force-closed so the pre-preemption
  resident set rebuilds on demand — the fleet never ends up with BOTH
  models down because one gamble failed.

``hbm_squeeze`` chaos stage: an injected raise at the headroom read
collapses observed headroom to 0, forcing the overcommit path without a
real device — the bench and the chaos tests drive preemption on CPU.

No jax imports; everything here runs on admission (source/HTTP) threads.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

from video_features_tpu.runtime import faults as faults_mod
from video_features_tpu.serve.lifecycle import DEFAULT_BUCKET


class PreemptionPlan:
    """The rollback token :meth:`Preemptor.ensure_room` returns: which
    residents were sacrificed for which beneficiary, and when."""

    def __init__(self, beneficiary: str, victims: List[str], at: float) -> None:
        self.beneficiary = beneficiary
        self.victims = list(victims)
        self.at = float(at)

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        return (f"PreemptionPlan(beneficiary={self.beneficiary!r}, "
                f"victims={self.victims!r})")


class Preemptor:
    """Admission-time HBM arbiter over the resident extractor pool.

    Collaborators are injected (ledger, cost model, pool, a
    ``breaker_for(ft)`` accessor, a headroom callable, a queued-work
    callable, a clock), so the ranking/fit logic is testable — and
    benchable — without a daemon or a device."""

    def __init__(
        self,
        ledger: Any,
        cost_model: Any,
        pool: Any,
        breaker_for: Callable[[str], Any],
        headroom_fn: Optional[Callable[[], Optional[int]]] = None,
        queued_fn: Optional[Callable[[], Dict[str, Dict[str, Any]]]] = None,
        hbm_budget_bytes: int = 0,
        cooldown_s: float = 30.0,
        min_residency_s: float = 60.0,
        clock: Callable[[], float] = time.monotonic,
        metrics: Any = None,
        manifest: Any = None,
    ) -> None:
        self.ledger = ledger
        self.cost_model = cost_model
        self.pool = pool
        self.breaker_for = breaker_for
        self.headroom_fn = headroom_fn
        self.queued_fn = queued_fn
        self.hbm_budget_bytes = max(int(hbm_budget_bytes or 0), 0)
        self.cooldown_s = max(float(cooldown_s), 0.0)
        self.min_residency_s = max(float(min_residency_s), 0.0)
        self._clock = clock
        self._metrics = metrics
        self._manifest = manifest
        self._lock = threading.Lock()
        self._last_preempt: Optional[float] = None
        self._preemptions = 0  # lifetime count, for /healthz

    # -- fit check -------------------------------------------------------

    def _headroom(self) -> Optional[int]:
        """Live headroom bytes, or None when there is no signal. The
        ``hbm_squeeze`` chaos stage collapses it to 0 — the fake device-
        memory emergency the overcommit tests and bench are built on."""
        try:
            faults_mod.fire("hbm_squeeze")
        except Exception:  # noqa: BLE001 - any injected kind means 'squeezed'
            return 0
        if self.headroom_fn is not None:
            h = self.headroom_fn()
            if h is not None:
                return int(h)
        if self.hbm_budget_bytes > 0:
            resident = self.pool.feature_types()
            return self.hbm_budget_bytes - int(
                self.ledger.projected_resident_bytes(resident)
            )
        return None

    def check(self, feature_type: str) -> Tuple[str, int, Optional[int]]:
        """``(verdict, needed_bytes, available_bytes)`` for admitting one
        request of ``feature_type``. Verdicts: ``"fits"`` (resident
        already, or projected to fit), ``"overcommit"`` (projected NOT to
        fit), ``"unknown"`` (no projection or no headroom signal — CPU
        backends land here and preemption stays out of the way)."""
        if feature_type in self.pool.feature_types():
            return ("fits", 0, None)
        proj = self.ledger.hbm_projection().get(feature_type)
        if not proj:
            return ("unknown", 0, None)
        needed = int(proj.get("resident", 0))
        available = self._headroom()
        if available is None:
            return ("unknown", needed, None)
        return ("fits" if needed <= available else "overcommit",
                needed, available)

    # -- value ranking ---------------------------------------------------

    def value_score(self, feature_type: str) -> float:
        """How much the fleet loses by evicting this resident now. See
        the module docstring for the three factors; all three floor at
        1.0 so an idle, cold, priority-0 model scores exactly 1.0 and
        equal-value ties rank purely by name (stable)."""
        stats = {}
        if self.queued_fn is not None:
            stats = self.queued_fn().get(feature_type, {}) or {}
        priority = 1.0 + float(stats.get("max_priority", 0) or 0)
        count = int(stats.get("count", 0) or 0)
        buckets = list(stats.get("buckets", [])) or [DEFAULT_BUCKET]
        demand_s = sum(
            float(self.cost_model.predict((feature_type, b), 1))
            for b in buckets
        ) / max(len(buckets), 1)
        demand = 1.0 + count * demand_s
        warm = 1 + sum(
            1 for e in self.ledger.entries()
            if e.get("model") == feature_type
        )
        return priority * demand * warm

    def _candidates(self, beneficiary: str, now: float) -> List[str]:
        """Residents eligible for eviction: not the beneficiary, and
        resident longer than the min-residency guard (a just-built model
        being torn down before serving a single group is pure thrash)."""
        built_at = getattr(self.pool, "built_at", {})
        out = []
        for ft in self.pool.feature_types():
            if ft == beneficiary:
                continue
            at = built_at.get(ft)
            if at is not None and now - at < self.min_residency_s:
                continue
            out.append(ft)
        return out

    # -- the preemption itself -------------------------------------------

    def ensure_room(self, feature_type: str) -> Optional[PreemptionPlan]:
        """Try to make the overcommitted ``feature_type`` fit by evicting
        the lowest-value residents. Returns the :class:`PreemptionPlan`
        when victims were sacrificed, None when nothing was done — which
        the caller must re-:meth:`check` to distinguish "already fits"
        from "could not help" (cooldown, no eligible victims, or not
        enough reclaimable bytes)."""
        verdict, needed, available = self.check(feature_type)
        if verdict != "overcommit":
            return None
        now = self._clock()
        with self._lock:
            if (
                self._last_preempt is not None
                and now - self._last_preempt < self.cooldown_s
            ):
                return None  # hysteresis: one preemption per cooldown
            proj = self.ledger.hbm_projection()
            candidates = self._candidates(feature_type, now)
            candidates.sort(key=lambda ft: (self.value_score(ft), ft))
            victims: List[str] = []
            reclaimed = 0
            for ft in candidates:
                if needed <= (available or 0) + reclaimed:
                    break
                victims.append(ft)
                reclaimed += int(proj.get(ft, {}).get("resident", 0))
            if needed > (available or 0) + reclaimed:
                return None  # even a full sweep cannot fit it: reject
            self._last_preempt = now
            self._preemptions += len(victims)
        for victim in victims:
            # trip FIRST: the victim's admissions start deferring before
            # its extractor vanishes, so no request can slip into a
            # build-race against the beneficiary
            self.breaker_for(victim).trip()
            self.pool.evict(victim)
            if self._metrics is not None:
                self._metrics.inc(f"preemptions.{victim}")
            if self._manifest is not None:
                self._manifest.event(
                    "preempted", feature_type=victim,
                    beneficiary=feature_type, value=round(
                        self.value_score(victim), 4),
                )
        return PreemptionPlan(feature_type, victims, now)

    def rollback(self, plan: PreemptionPlan) -> None:
        """The beneficiary's build failed: hand the evicted victims
        their slots back by force-closing their breakers — the next
        request rebuilds each on demand (warm compile cache), restoring
        the pre-preemption resident set without a cooldown penalty."""
        for victim in plan.victims:
            self.breaker_for(victim).force_close()
            if self._manifest is not None:
                self._manifest.event(
                    "preemption_rollback", feature_type=victim,
                    beneficiary=plan.beneficiary,
                )

    def snapshot(self) -> Dict[str, Any]:
        """The /healthz block."""
        with self._lock:
            return {
                "preemptions": self._preemptions,
                "cooldown_s": self.cooldown_s,
                "min_residency_s": self.min_residency_s,
            }


def simulate_overcommit(
    preemptor: Optional[Preemptor],
    bursts: Sequence[Tuple[str, int]],
    resident_fits: Callable[[str], bool],
    service_s: float = 1.0,
    deadline_s: float = 2.5,
    rewarm_s: float = 0.5,
) -> List[Dict[str, Any]]:
    """Deterministic replay of a mixed-model burst against an HBM wall
    (the ``serve_preemption`` bench part and the pinned A/B tests — the
    ``simulate_dispatch`` idiom from serve/scheduler.py).

    ``bursts`` is ``[(feature_type, n_requests), ...]`` in arrival
    order; ``resident_fits(ft)`` says whether ``ft`` fits WITHOUT
    preemption (the wall). A burst that fits dispatches as one fused
    group: every member's latency is ``service_s``. A burst that does
    not fit either clears the wall through ``preemptor.ensure_room``
    (preemption ON — its first group additionally pays the ``rewarm_s``
    eviction + rebuild toll) or, with no preemptor (preemption OFF —
    today's behavior), every member is rejected and scored as a
    deadline miss at ``deadline_s``. Returns one record per request:
    ``{"feature_type", "met", "latency_s"}``."""
    out: List[Dict[str, Any]] = []
    room: Dict[str, bool] = {}
    toll: Dict[str, float] = {}
    for ft, n in bursts:
        fits = room.get(ft)
        if fits is None:
            fits = bool(resident_fits(ft))
            toll[ft] = 0.0
            if not fits and preemptor is not None:
                if preemptor.ensure_room(ft) is not None \
                        or preemptor.check(ft)[0] == "fits":
                    fits = True
                    toll[ft] = float(rewarm_s)
            room[ft] = fits
        latency = float(service_s) + toll.get(ft, 0.0)
        toll[ft] = 0.0  # only the first fused group pays the re-warm
        for _ in range(int(n)):
            out.append({
                "feature_type": ft,
                "met": bool(fits) and latency <= deadline_s,
                "latency_s": round(latency if fits else deadline_s, 6),
            })
    return out
