"""Extractor supervision: watchdog-bounded group execution and a
per-feature-type circuit breaker (ISSUE 8).

A resident daemon's failure modes differ from a batch run's: a wedged
extractor (hung decode on the dispatcher thread, a device runtime that
stopped answering) blocks EVERY model's traffic, and a model that fails
every group burns chip time re-failing while healthy models queue behind
it. Two small mechanisms bound both:

- :class:`Watchdog` runs each group body on a supervised worker thread
  and bounds its wall time (``--group_timeout_s``). A timed-out worker
  is *abandoned* (Python threads cannot be killed) — the group's
  requests fail ``transient``, the dispatcher moves on, and the daemon
  tears the extractor down so the abandoned thread's model state is
  never reused. ``timeout_s <= 0`` disables the thread hop entirely
  (the PR 7 inline behavior).
- :class:`CircuitBreaker`, one per feature type: ``breaker_threshold``
  consecutive group-level failures (build crash, loop crash, watchdog
  timeout — NOT per-video failures inside a healthy group) open it;
  while open, new requests for that model get 503/spool-deferral while
  every other model serves normally. After ``breaker_cooldown_s`` it
  half-opens: exactly ONE admitted group becomes the probe
  (:meth:`try_probe`), the daemon re-builds the evicted extractor and
  re-warms it through the declared ``--warmup`` pairs, and the probe's
  outcome closes or re-opens the breaker. ``/healthz`` reports every
  breaker's state.

The clock is injectable (the daemon shares its admission clock), so the
tier-1 breaker tests advance time instead of sleeping. All state is
lock-guarded; the module sits in graftcheck's GC301 thread-root scope
with zero waivers.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Callable, Dict, Optional

BREAKER_STATES = ("closed", "open", "half_open")


class ModelUnavailable(RuntimeError):
    """Admission refused because this feature type's breaker is open.
    Scoped to ONE model: the HTTP source answers 503 with Retry-After,
    the spool source defers the file — other models are unaffected."""

    def __init__(
        self,
        feature_type: str,
        retry_after_s: float,
        reason: Optional[str] = None,
    ) -> None:
        super().__init__(
            reason
            or f"model {feature_type!r} unavailable (circuit breaker open); "
               f"retry in {retry_after_s:.1f}s"
        )
        self.feature_type = feature_type
        self.retry_after_s = float(retry_after_s)


class GroupTimeout(TimeoutError):
    """The watchdog bound fired: the group exceeded ``group_timeout_s``
    wall time. A TimeoutError so :func:`~video_features_tpu.runtime.
    faults.classify_error` files it ``transient`` — re-submitting after
    the extractor is rebuilt may well succeed."""

    stage = "dispatch"


class CircuitBreaker:
    """closed -> (K consecutive failures) -> open -> (cooldown) ->
    half_open -> one probe -> closed | open. Failure/success here means
    GROUP-level outcome; per-video failures inside a completed group
    never touch the breaker."""

    def __init__(
        self,
        threshold: int = 3,
        cooldown_s: float = 30.0,
        clock: Callable[[], float] = time.monotonic,
    ) -> None:
        self.threshold = max(int(threshold), 1)
        self.cooldown_s = max(float(cooldown_s), 0.0)
        self._clock = clock
        self._lock = threading.Lock()
        self._state = "closed"
        self._failures = 0  # consecutive group-level failures
        self._opened_at = 0.0
        self._probing = False
        self._opens = 0  # lifetime count, for /healthz trend reading

    def _state_locked(self, now: float) -> str:
        if self._state == "open" and now - self._opened_at >= self.cooldown_s:
            self._state = "half_open"
        return self._state

    def state(self) -> str:
        with self._lock:
            return self._state_locked(self._clock())

    def allow_request(self) -> bool:
        """Admission gate: closed always admits; half-open admits until
        a probe is in flight (the admitted request BECOMES the probe at
        dispatch); open admits nothing."""
        with self._lock:
            st = self._state_locked(self._clock())
            return st == "closed" or (st == "half_open" and not self._probing)

    def retry_after_s(self) -> float:
        with self._lock:
            now = self._clock()
            if self._state_locked(now) != "open":
                return 0.0
            return max(self._opened_at + self.cooldown_s - now, 0.0)

    def try_probe(self) -> bool:
        """Claim the single half-open probe slot; the caller's group is
        the probe and MUST report back via record_success/failure."""
        with self._lock:
            if self._state_locked(self._clock()) == "half_open" and not self._probing:
                self._probing = True
                return True
            return False

    def record_success(self) -> None:
        with self._lock:
            self._state = "closed"
            self._failures = 0
            self._probing = False

    def record_ignored(self) -> None:
        """A group outcome that must not move the breaker either way:
        input-classified crashes (corrupt media, resource caps) say
        nothing about model health. In half-open this releases the
        probe slot WITHOUT a verdict — the hostile input consumed the
        probe group, so the next admitted group re-probes; the breaker
        stays half-open rather than closing on unproven hardware or
        re-opening on bad traffic. No-op when closed (the consecutive-
        failure counter is neither advanced nor reset: an input error
        between two real infra failures must not mask the streak, and
        ignoring it is exactly the point)."""
        with self._lock:
            self._probing = False

    def trip(self) -> None:
        """Force-open the breaker (HBM-aware preemption, ISSUE 18): the
        preemptor evicts a victim extractor to make room for a burst and
        trips its breaker so the victim's traffic defers (503 / spool
        backoff) instead of racing an immediate rebuild into the memory
        it just freed. The re-warm rides the normal cooldown ->
        half-open -> probe path, so recovery is observable in /healthz
        exactly like a failure-opened breaker."""
        with self._lock:
            self._state = "open"
            self._opened_at = self._clock()
            self._probing = False
            self._opens += 1

    def force_close(self) -> None:
        """Roll the breaker back to closed (preemption rollback: the
        beneficiary's build failed, so the victim should serve again
        without waiting out a cooldown it did nothing to deserve)."""
        with self._lock:
            self._state = "closed"
            self._failures = 0
            self._probing = False

    def record_failure(self) -> bool:
        """One group-level failure. Returns True when this failure
        (re)opened the breaker — the daemon's cue to tear the resident
        extractor down."""
        with self._lock:
            now = self._clock()
            st = self._state_locked(now)
            self._failures += 1
            if st == "half_open" or self._failures >= self.threshold:
                self._state = "open"
                self._opened_at = now
                self._probing = False
                self._opens += 1
                return True
            return False

    def snapshot(self) -> Dict[str, Any]:
        """The /healthz block for this model."""
        with self._lock:
            now = self._clock()
            st = self._state_locked(now)
            out: Dict[str, Any] = {
                "state": st,
                "consecutive_failures": self._failures,
                "opens": self._opens,
            }
            if st == "open":
                out["retry_after_s"] = round(
                    max(self._opened_at + self.cooldown_s - now, 0.0), 3
                )
            return out


class Watchdog:
    """Bounds one group's extraction wall time by running the group body
    on a fresh supervised worker thread and joining with a timeout.

    On timeout the worker is abandoned, never joined — it may still be
    blocked in a hung decode or device call; the daemon evicts the
    extractor it was using so nothing shares state with it — and
    :class:`GroupTimeout` is raised on the dispatcher thread. A fresh
    thread per group keeps this allocation-trivial next to extraction
    itself and means a wedged worker can never poison the next group."""

    def __init__(self, timeout_s: float = 0.0) -> None:
        self.timeout_s = float(timeout_s)
        self._lock = threading.Lock()
        self._timeouts = 0  # lifetime count, surfaced in /healthz

    def timeouts(self) -> int:
        with self._lock:
            return self._timeouts

    def run(self, fn: Callable[[], Any]) -> Any:
        if self.timeout_s <= 0:
            return fn()  # unbounded: the PR 7 inline path
        box: Dict[str, Any] = {}
        done = threading.Event()

        def body() -> None:
            try:
                box["result"] = fn()
            except BaseException as exc:  # noqa: BLE001 - re-raised on the dispatcher
                box["exc"] = exc
            finally:
                done.set()

        worker = threading.Thread(target=body, name="serve-group", daemon=True)
        worker.start()
        if not done.wait(self.timeout_s):
            with self._lock:
                self._timeouts += 1
            raise GroupTimeout(
                f"group exceeded group_timeout_s={self.timeout_s:g}s; "
                "worker abandoned, extractor will be rebuilt"
            )
        exc = box.get("exc")
        if exc is not None:
            raise exc
        return box.get("result")
