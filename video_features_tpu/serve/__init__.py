"""The long-lived extraction daemon (``video-features-tpu serve``).

Modules: :mod:`.lifecycle` (request records), :mod:`.batcher`
(bucket-keyed coalescing admission), :mod:`.daemon` (extractor pool +
wiring + CLI), :mod:`.server` (HTTP source), :mod:`.sources` (spool
source). Import via the submodules — this package intentionally
re-exports nothing, so importing `video_features_tpu.serve` never drags
in jax (lifecycle/batcher are jax-free; only daemon.py touches models).
"""
