"""Minimal HTTP/JSON request source on stdlib ``http.server``.

Endpoints (the whole surface — this is an admission door, not a web
framework; anything fancier belongs behind a real proxy):

- ``POST /v1/extract`` — body ``{"feature_type": ..., "video_path": ...,
  "bucket"?: "WxH", "id"?: ..., "priority"?: 0..9, "deadline_ms"?: N}``;
  202 + the queued lifecycle record, 400 on a malformed request
  (recorded nowhere — it never had an identity), 503 + Retry-After when
  the bounded admission queue is full OR this feature type's circuit
  breaker is open (recorded ``rejected``; the client owns the retry).
  With ``--cache_dir``, a content-addressed cache hit returns 202 with
  the record already terminal ``done`` (features listed) — no dispatch.
  The multi-model form replaces ``feature_type`` with ``"feature_types":
  [...]`` (a LIST): one sub-request per model (ids ``<base>.<model>``),
  the video decoded ONCE for all of them, 202 + an aggregate body
  ``{"fanout": true, "requests": {<model>: <record>, ...}}`` whose
  members are polled individually via ``GET /v1/requests/<sub-id>``.
- ``GET /v1/requests/<id>`` — the lifecycle record (memory, falling back
  to the durable result JSON); 404 for unknown ids.
- ``DELETE /v1/requests/<id>`` — cancel: 200 + the terminal record when
  the request was still queued (idempotent: repeating the DELETE of an
  already-cancelled request is 200 again), 202 + ``cancel_requested``
  when it is already dispatched (honored at the group boundary), 409 +
  the record when already terminal in another state (done/failed/
  rejected/expired — too late to cancel), 404 for unknown ids.
- ``GET /healthz`` — queue depth, per-state counts, warm model list,
  scheduler name, per-model circuit-breaker state.
- ``GET /metrics`` — Prometheus text exposition (format 0.0.4) of the
  daemon's metrics registry plus live serve families (breaker state,
  SLO quantiles, uptime); stdlib-rendered, no client library. See
  docs/observability.md "Live serve metrics".
- ``GET /v1/stats`` — the JSON twin of /metrics: /healthz plus the SLO
  window digest, cost-model snapshot, and raw metrics snapshot.

ThreadingHTTPServer: handlers run on per-connection threads, so
everything they touch (daemon.submit -> tracker/batcher) is lock-guarded
— the package sits in graftcheck's GC301 thread-root scope.
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Dict, Tuple

from video_features_tpu.serve.lifecycle import BadRequest, InvalidMedia

MAX_BODY_BYTES = 1 << 20  # a request is a few hundred bytes; 1 MiB is hostile


class ServeHandler(BaseHTTPRequestHandler):
    """One request in, one JSON document out. The daemon reference lives
    on the server object (set by :func:`start_http_server`)."""

    server_version = "vft-serve/1.0"
    protocol_version = "HTTP/1.1"

    def _send(self, code: int, body: Dict[str, Any], retry_after: float = 0.0) -> None:
        data = json.dumps(body, sort_keys=True).encode("utf-8")
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(data)))
        if retry_after > 0:
            self.send_header("Retry-After", str(max(int(retry_after), 1)))
        self.end_headers()
        self.wfile.write(data)

    def _send_text(self, code: int, text: str, content_type: str) -> None:
        data = text.encode("utf-8")
        self.send_response(code)
        self.send_header("Content-Type", content_type)
        self.send_header("Content-Length", str(len(data)))
        self.end_headers()
        self.wfile.write(data)

    def do_POST(self) -> None:  # noqa: N802 - http.server API
        if self.path.rstrip("/") != "/v1/extract":
            self._send(404, {"error": f"no such endpoint: {self.path}"})
            return
        daemon = self.server.daemon  # type: ignore[attr-defined]
        try:
            length = int(self.headers.get("Content-Length") or 0)
        except ValueError:
            length = -1
        if length < 0 or length > MAX_BODY_BYTES:
            self._send(400, {"error": "missing or oversized Content-Length"})
            return
        try:
            payload = json.loads(self.rfile.read(length).decode("utf-8"))
        except (ValueError, UnicodeDecodeError) as exc:
            self._send(400, {"error": f"body is not valid JSON: {exc}"})
            return
        try:
            rec = daemon.submit(payload, source="http")
        except InvalidMedia as exc:
            # before the BadRequest catch (InvalidMedia IS a BadRequest):
            # 422 says "well-formed request, unprocessable media" — the
            # client should fix the FILE, not the request shape, and the
            # durable rejected record rides along so the caller can poll
            # /requests/<id> later and see the same terminal verdict
            self._send(
                422,
                {"error": str(exc), "reason_code": "invalid_media",
                 "record": exc.record},
            )
            return
        except BadRequest as exc:
            self._send(400, {"error": str(exc)})
            return
        except Exception as exc:  # noqa: BLE001 - QueueFull/ModelUnavailable without importing serve internals here
            name = type(exc).__name__
            if name == "QueueFull":
                self._send(
                    503,
                    {"error": str(exc), "queue_depth": daemon.batcher.depth()},
                    retry_after=daemon.scfg.max_batch_wait_ms / 1000.0 * 2,
                )
                return
            if name == "ModelUnavailable":
                self._send(
                    503,
                    {"error": str(exc),
                     "feature_type": getattr(exc, "feature_type", None)},
                    retry_after=getattr(exc, "retry_after_s", 1.0),
                )
                return
            raise
        self._send(202, rec)

    def do_DELETE(self) -> None:  # noqa: N802 - http.server API
        daemon = self.server.daemon  # type: ignore[attr-defined]
        prefix = "/v1/requests/"
        if not self.path.startswith(prefix):
            self._send(404, {"error": f"no such endpoint: {self.path}"})
            return
        rid = self.path[len(prefix):].rstrip("/")
        rec = daemon.cancel(rid)
        if rec is None:
            self._send(404, {"error": f"unknown request id {rid!r}"})
        elif rec.get("state") == "cancelled":
            self._send(200, rec)
        elif rec.get("cancel_requested"):
            self._send(202, rec)
        else:  # already terminal: too late to cancel, record stands
            self._send(409, rec)

    def do_GET(self) -> None:  # noqa: N802 - http.server API
        daemon = self.server.daemon  # type: ignore[attr-defined]
        path = self.path.rstrip("/")
        if path == "/healthz":
            self._send(200, daemon.status())
            return
        if path == "/metrics":
            # the content type Prometheus scrapers negotiate for the
            # 0.0.4 text format
            self._send_text(
                200, daemon.metrics_text(),
                "text/plain; version=0.0.4; charset=utf-8",
            )
            return
        if path == "/v1/stats":
            self._send(200, daemon.stats())
            return
        prefix = "/v1/requests/"
        if self.path.startswith(prefix):
            rid = self.path[len(prefix):]
            rec = daemon.tracker.get(rid)
            if rec is None:
                self._send(404, {"error": f"unknown request id {rid!r}"})
            else:
                self._send(200, rec)
            return
        self._send(404, {"error": f"no such endpoint: {self.path}"})

    def log_message(self, format: str, *args: Any) -> None:  # noqa: A002
        pass  # the daemon's heartbeat/manifest are the log; not per-request access lines


def start_http_server(daemon: Any, host: str, port: int) -> Tuple[ThreadingHTTPServer, threading.Thread]:
    """Bind (``port=0`` -> ephemeral, how the tests run), attach the
    daemon, serve on a background thread. Caller owns shutdown()."""
    server = ThreadingHTTPServer((host, port), ServeHandler)
    server.daemon_threads = True
    server.daemon = daemon  # type: ignore[attr-defined]
    thread = threading.Thread(
        target=server.serve_forever, name="serve-http", daemon=True
    )
    thread.start()
    return server, thread
