"""Cross-request batched admission: the daemon's coalescing queue.

The tentpole mechanism (ISSUE 7): incoming requests are keyed by
``(feature_type, spatial bucket)`` — the same bucket-keyed aggregation
key the ``--video_batch`` group path fuses on — and same-key requests
coalesce into groups of up to ``max_group_size`` under a latency
deadline of ``max_batch_wait_ms``. A group dispatches when it fills OR
when its oldest member's deadline expires, whichever comes first; so a
burst of N same-key requests crosses the chip in ceil(N / group) fused
dispatches while a lone request waits at most one deadline.

One dispatcher thread executes groups serially (the Arachne framing:
one resident scheduler multiplexing model stages over a fixed chip
pool); WHICH ready group runs next is the pluggable scheduler's call
(serve/scheduler.py, ISSUE 8): EDF across keys with priority tiers and
aging by default, FIFO as the A/B baseline. Admission stamps each
request's ``admitted_at``/``deadline_at`` on this controller's clock so
scheduler ranks and fake-clock tests share one time base. Sources admit
concurrently from their own threads. The admission
queue is bounded (``max_queue``, counting every request admitted but
not yet terminal) — past the bound :meth:`admit` raises
:class:`QueueFull`, which the HTTP source turns into a 503 and the
spool source into leave-it-for-the-next-poll backpressure.

Determinism for tests: the clock is injectable and the deadline logic
is a pure sweep (:meth:`take_ready`), so tier-1 tests drive coalescing
with a fake ``now`` and never sleep.

All shared state lives behind one condition variable; the module is in
graftcheck's GC301 thread-root scope and carries zero waivers.
"""

from __future__ import annotations

import threading
import time
from collections import OrderedDict
from typing import Any, Callable, Dict, List, Optional, Tuple

from video_features_tpu.serve.lifecycle import ExtractionRequest
from video_features_tpu.serve.scheduler import EdfScheduler

Key = Tuple[str, str]
Group = Tuple[Key, List[ExtractionRequest]]


class QueueFull(RuntimeError):
    """Admission rejected: the bounded queue is at ``max_queue`` (or the
    controller is closed). The caller owns the reject record."""


class AdmissionController:
    """Bucket-keyed coalescing queue + single dispatcher thread.

    ``dispatch`` is called on the dispatcher thread with one
    ``(key, requests)`` group at a time; it must not raise (the daemon's
    dispatch wrapper records per-request failures itself), but a raise
    is still contained here so one poisoned group can never kill the
    serving loop."""

    def __init__(
        self,
        dispatch: Callable[[Key, List[ExtractionRequest]], None],
        max_group_size: int = 8,
        max_batch_wait_s: float = 0.05,
        max_queue: int = 256,
        clock: Callable[[], float] = time.monotonic,
        metrics: Any = None,
        scheduler: Optional[EdfScheduler] = None,
    ) -> None:
        self._dispatch = dispatch
        self.max_group_size = max(int(max_group_size), 1)
        self.max_batch_wait_s = max(float(max_batch_wait_s), 0.0)
        self.max_queue = max(int(max_queue), 1)
        self._clock = clock
        self._metrics = metrics
        self._scheduler = scheduler if scheduler is not None else EdfScheduler()
        self._cond = threading.Condition()
        # key -> open coalescing buffer; insertion-ordered so expiry
        # sweeps oldest-first (a buffer's deadline is set when its FIRST
        # member arrives and never extended by later ones)
        self._buffers: "OrderedDict[Key, List[ExtractionRequest]]" = OrderedDict()
        self._deadlines: Dict[Key, float] = {}
        # ready groups in the order they became ready; the scheduler
        # picks ACROSS this list at each dispatch, index = arrival
        # tie-break, so FIFO scheduling degenerates to the old deque
        self._ready: List[Group] = []
        self._depth = 0  # admitted, not yet handed back as terminal
        self._closed = False
        self._thread: Optional[threading.Thread] = None
        self._errors = 0

    # -- admission (any thread) -----------------------------------------

    def admit(self, req: ExtractionRequest) -> None:
        """Queue one request for coalescing; raises :class:`QueueFull`
        past ``max_queue`` (bounded admission is the backpressure fix —
        an unbounded daemon queue turns a burst into an OOM)."""
        with self._cond:
            if self._closed:
                raise QueueFull("daemon is shutting down")
            if self._depth >= self.max_queue:
                raise QueueFull(
                    f"admission queue full ({self._depth}/{self.max_queue})"
                )
            self._depth += 1
            # absolute scheduling times on THIS controller's clock: the
            # scheduler's ranks and the dispatch-time expiry check both
            # read these, never the wall clock
            req.admitted_at = self._clock()
            if req.deadline_ms is not None:
                req.deadline_at = req.admitted_at + req.deadline_ms / 1000.0
            key = req.key()
            buf = self._buffers.setdefault(key, [])
            buf.append(req)
            if len(buf) >= self.max_group_size:
                del self._buffers[key]
                self._deadlines.pop(key, None)
                self._ready.append((key, buf))
            elif len(buf) == 1:
                self._deadlines[key] = self._clock() + self.max_batch_wait_s
            self._gauge_locked()
            self._cond.notify_all()

    def depth(self) -> int:
        with self._cond:
            return self._depth

    def cancel(self, request_id: str) -> Optional[ExtractionRequest]:
        """Pull one still-queued request out of the admission queue —
        open coalescing buffer or ready group — returning it so the
        caller records the terminal ``cancelled`` state. None when the
        request is not here (already dispatched, or unknown): dispatched
        requests are the daemon's cancel-requested set, checked at the
        group boundary."""
        with self._cond:
            for key, buf in list(self._buffers.items()):
                for i, r in enumerate(buf):
                    if r.id == request_id:
                        buf.pop(i)
                        if not buf:
                            del self._buffers[key]
                            self._deadlines.pop(key, None)
                        self._depth -= 1
                        self._gauge_locked()
                        self._cond.notify_all()
                        return r
            for gi, (key, reqs) in enumerate(self._ready):
                for i, r in enumerate(reqs):
                    if r.id == request_id:
                        reqs.pop(i)
                        if not reqs:
                            self._ready.pop(gi)
                        self._depth -= 1
                        self._gauge_locked()
                        self._cond.notify_all()
                        return r
        return None

    # -- deadline sweep (pure given `now`; lock held by callers) --------

    def _flush_expired_locked(self, now: float) -> None:
        for key in [k for k, d in self._deadlines.items() if d <= now]:
            buf = self._buffers.pop(key, None)
            del self._deadlines[key]
            if buf:
                self._ready.append((key, buf))

    def _flush_all_locked(self) -> None:
        while self._buffers:
            key, buf = self._buffers.popitem(last=False)
            self._deadlines.pop(key, None)
            self._ready.append((key, buf))

    def take_ready(self, now: Optional[float] = None) -> List[Group]:
        """Drain every group ready at ``now`` (full groups plus buffers
        whose deadline has passed), in scheduler dispatch order. The
        deterministic surface the fake-clock tests drive directly."""
        with self._cond:
            now = self._clock() if now is None else now
            self._flush_expired_locked(now)
            out = self._scheduler.order(self._ready, now)
            self._ready.clear()
            return out

    def _next_deadline_locked(self) -> Optional[float]:
        return min(self._deadlines.values()) if self._deadlines else None

    # -- dispatcher thread ----------------------------------------------

    def start(self) -> None:
        with self._cond:
            if self._thread is not None:
                return
            self._thread = threading.Thread(
                target=self._loop, name="serve-batcher", daemon=True
            )
        self._thread.start()

    def _loop(self) -> None:
        while True:
            with self._cond:
                group: Optional[Group] = None
                while group is None:
                    now = self._clock()
                    self._flush_expired_locked(now)
                    if self._ready:
                        group = self._ready.pop(self._scheduler.pick(self._ready, now))
                        break
                    if self._closed:
                        return
                    nd = self._next_deadline_locked()
                    timeout = None if nd is None else max(nd - self._clock(), 0.0)
                    self._cond.wait(timeout=timeout)
            self._run_group(group)

    def _run_group(self, group: Group) -> None:
        key, reqs = group
        if self._metrics is not None:
            self._metrics.set_gauge("groups_inflight", 1)
            self._metrics.inc("groups_dispatched")
        try:
            self._dispatch(key, reqs)
        except Exception:  # noqa: BLE001 - one bad group must not kill serving
            import traceback

            with self._cond:
                self._errors += 1
            print(f"serve: dispatch of group {key} died (requests survive "
                  f"as 'failed' only if the dispatcher recorded them):")
            traceback.print_exc()
        finally:
            if self._metrics is not None:
                self._metrics.set_gauge("groups_inflight", 0)
            with self._cond:
                self._depth -= len(reqs)
                self._gauge_locked()
                self._cond.notify_all()

    # -- shutdown --------------------------------------------------------

    def close(self, drain: bool = True) -> List[ExtractionRequest]:
        """Stop admitting. ``drain=True`` (the default): flush every
        partial buffer and let the dispatcher finish the backlog before
        returning — no admitted request is ever silently dropped.
        ``drain=False``: return the undispatched requests so the caller
        can record them rejected."""
        with self._cond:
            self._closed = True
            if drain:
                self._flush_all_locked()
                dropped: List[ExtractionRequest] = []
            else:
                self._flush_all_locked()
                dropped = [r for _, buf in self._ready for r in buf]
                self._depth -= len(dropped)
                self._ready.clear()
            self._cond.notify_all()
            thread = self._thread
        if thread is not None:
            thread.join()
        elif drain:
            # never started (warmup-only runs, unit tests): drain inline
            for group in self.take_ready(now=float("inf")):
                self._run_group(group)
        return dropped

    def _gauge_locked(self) -> None:
        if self._metrics is not None:
            self._metrics.set_gauge("queue_depth.admission", self._depth)
            self._metrics.set_gauge(
                "queue_age_oldest_s", self._oldest_wait_locked(self._clock())
            )

    def _oldest_wait_locked(self, now: float) -> float:
        """Age of the oldest still-queued request (coalescing buffers +
        ready groups), 0.0 when the queue is empty — the head-of-line
        staleness signal for /metrics and the heartbeat."""
        oldest: Optional[float] = None
        for buf in self._buffers.values():
            if buf and buf[0].admitted_at is not None:
                t = buf[0].admitted_at
                oldest = t if oldest is None else min(oldest, t)
        for _, reqs in self._ready:
            if reqs and reqs[0].admitted_at is not None:
                t = reqs[0].admitted_at
                oldest = t if oldest is None else min(oldest, t)
        return max(now - oldest, 0.0) if oldest is not None else 0.0

    def oldest_wait_s(self) -> float:
        with self._cond:
            return self._oldest_wait_locked(self._clock())

    def queued_by_feature_type(self) -> Dict[str, Dict[str, Any]]:
        """Per-feature-type view of everything still queued (coalescing
        buffers + ready groups): ``{ft: {"count", "max_priority",
        "buckets"}}``. The preemptor's value score reads this — how much
        work is waiting for each model, at what priority tier, on which
        spatial buckets (the warm-executable affinity signal)."""
        with self._cond:
            out: Dict[str, Dict[str, Any]] = {}
            for key, buf in list(self._buffers.items()) + list(self._ready):
                ft, bucket = key
                stat = out.setdefault(
                    ft, {"count": 0, "max_priority": 0, "buckets": set()}
                )
                stat["count"] += len(buf)
                stat["buckets"].add(bucket)
                for r in buf:
                    pri = getattr(r, "priority", None)
                    if pri is not None and int(pri) > stat["max_priority"]:
                        stat["max_priority"] = int(pri)
            for stat in out.values():
                stat["buckets"] = sorted(stat["buckets"])
            return out
