"""Ring attention: exact context parallelism over a mesh axis.

The reference caps sequence length at whatever one GPU's memory holds —
its CLIP path materializes full (L, L) score matrices per head inside
torch MultiheadAttention, and its only parallelism is video-list
scatter (ref main.py:49-55). This module is the TPU-native long-context
story the reference has no analog of: shard the token axis over a mesh
axis, keep every chip's K/V shard resident, and rotate K/V shards around
the ICI ring with ``lax.ppermute`` while each chip folds them into the
FlashAttention online-softmax accumulator (ops/attention.py). After
``axis_size`` hops every Q shard has seen every KV shard: the result is
mathematically exact vs full attention (same softmax, different fp
accumulation order — tests assert 1e-5), with O(L/n) activation memory per
chip and compute/communication overlapped by XLA's async collective
scheduling.

Layout contract: (N, H, L, d) tensors with L sharded over ``axis_name``;
right-padding on L (to make it mesh-divisible) is masked via ``kv_len``
— global token positions >= kv_len contribute nothing, and padded query
rows compute garbage the caller slices off (parallel/sharding.py
``pad_batch_for`` convention).

``ring_attention`` is the per-shard collective (call under ``shard_map``);
``ring_attention_sharded`` wraps it for use inside a GSPMD-jitted model,
which is how the CLIP ViT runs it in ``--sharding mesh --mesh_context``
mode (models/clip/model.py::Attention).
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from ..ops.attention import (
    _finalize,
    accumulate_blockwise,
    init_carry,
    online_softmax_step,
)

# jax >= 0.6 exposes shard_map at top level with a `check_vma` kwarg; older
# releases keep it in jax.experimental.shard_map with the same flag named
# `check_rep`. Resolve both at import so the call site stays version-blind.
if hasattr(jax, "shard_map"):
    _shard_map, _CHECK_KW = jax.shard_map, "check_vma"
else:  # pragma: no cover - exercised on jax < 0.6 installs
    from jax.experimental.shard_map import shard_map as _shard_map

    _CHECK_KW = "check_rep"


def _axis_size(axis_name: str) -> int:
    # lax.axis_size is also a >= 0.6 addition; the bound axis size has
    # always been statically known inside shard_map, just unexported.
    if hasattr(lax, "axis_size"):
        return lax.axis_size(axis_name)
    from jax._src import core as _core  # pragma: no cover - jax < 0.6

    return _core.axis_frame(axis_name)


def ring_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    axis_name: str,
    kv_len: Optional[jnp.ndarray] = None,
    block_size: Optional[int] = None,
) -> jnp.ndarray:
    """Per-shard ring attention; must run under shard_map/pmap.

    ``q``/``k``/``v`` are this chip's (N, H, L_local, d) shards of the
    L-sharded tensors. ``kv_len`` is the *global* number of valid tokens
    (None = every position valid). Returns this chip's (N, H, L_local, d)
    output shard.

    ``block_size`` additionally chunks each arriving KV shard through the
    blockwise accumulator — the fully-composed long-context core: live
    score memory O(Lq_local * block_size) even when one chip's shard is
    itself too long for a single score matrix.
    """
    axis_size = _axis_size(axis_name)
    axis_index = lax.axis_index(axis_name)
    l_local = k.shape[2]
    scale = q.shape[-1] ** -0.5
    limit = None if kv_len is None else jnp.asarray(kv_len)
    perm = [(i, (i + 1) % axis_size) for i in range(axis_size)]

    def step(carry, hop):
        m, l, acc, k_cur, v_cur = carry
        # k_cur/v_cur started on chip (axis_index - hop): their global
        # token offset is that source chip's shard offset.
        src = (axis_index - hop) % axis_size
        if block_size is not None:
            m, l, acc = accumulate_blockwise(
                q, k_cur, v_cur, (m, l, acc), scale, block_size,
                offset=src * l_local, limit=limit,
            )
        else:
            if limit is None:
                kv_mask = None
            else:
                pos = src * l_local + jnp.arange(l_local)
                kv_mask = (pos < limit)[None, None, None, :]
            m, l, acc = online_softmax_step(
                q, k_cur, v_cur, m, l, acc, scale, kv_mask=kv_mask
            )
        # Rotate KV shards one hop around the ring (ICI neighbor exchange).
        # scan needs a uniform carry, so the final hop also permutes; that
        # last exchange restores the original shard placement.
        k_nxt = lax.ppermute(k_cur, axis_name, perm)
        v_nxt = lax.ppermute(v_cur, axis_name, perm)
        return (m, l, acc, k_nxt, v_nxt), None

    m, l, acc = init_carry(q)
    (m, l, acc, _, _), _ = lax.scan(
        step, (m, l, acc, k, v), jnp.arange(axis_size)
    )
    return _finalize(m, l, acc, q.dtype)


def ring_attention_sharded(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    mesh: Mesh,
    axis_name: str = "data",
    kv_len: Optional[jnp.ndarray] = None,
    head_axis: Optional[str] = None,
    block_size: Optional[int] = None,
) -> jnp.ndarray:
    """Global-view ring attention: shard_map over ``mesh[axis_name]``.

    Callable from inside a GSPMD-jitted function: L (axis 2) is sharded
    over ``axis_name``, N/d stay replicated relative to that axis, and
    the kernel body runs per-shard with explicit ppermute hops. L must
    divide by the axis size (pad + ``kv_len`` otherwise).

    ``head_axis`` additionally shards the head axis (axis 1) over that
    mesh axis — the CP x TP composition: Megatron-sharded q/k/v arrive
    with heads already split over 'model', and the ring runs
    per-head-shard with no cross-axis traffic.
    """
    for name, t in (("q", q), ("k", k), ("v", v)):
        if t.shape[2] % mesh.shape[axis_name]:
            raise ValueError(
                f"{name} token axis {t.shape[2]} not divisible by mesh axis "
                f"'{axis_name}' ({mesh.shape[axis_name]}); pad and pass kv_len"
            )
    if head_axis is not None and q.shape[1] % mesh.shape[head_axis]:
        raise ValueError(
            f"head axis {q.shape[1]} not divisible by mesh axis "
            f"'{head_axis}' ({mesh.shape[head_axis]})"
        )
    spec = P(None, head_axis, axis_name, None)
    fn = _shard_map(
        partial(ring_attention, axis_name=axis_name, kv_len=kv_len,
                block_size=block_size),
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
        **{_CHECK_KW: False},
    )
    return fn(q, k, v)


def make_context_parallel_core(
    mesh: Mesh, axis_name: str = "data", head_axis: Optional[str] = "model",
    block_size: Optional[int] = None,
):
    """An ``attn_core(q, k, v) -> out`` for transformer models running in
    ``--sharding mesh --mesh_context`` mode (models/clip/model.py).

    Handles the ragged edge: the token axis (e.g. the ViT's grid*grid+1 =
    50/197 patch tokens) rarely divides the mesh axis, so q/k/v are
    right-padded to the next multiple, the pad KV positions are masked out
    of the softmax via ``kv_len``, and the pad query rows are sliced off
    the result. ``head_axis`` entries absent from the mesh are ignored.

    ``block_size`` chunks each arriving KV shard through the blockwise
    accumulator (ring x flash). CLIP's builder leaves it None — 50/197
    tokens fit one score matrix per hop — but models with long token
    axes pass it to bound live-score memory at O(Lq_local * block).
    """
    if head_axis is not None and head_axis not in mesh.shape:
        head_axis = None
    n = mesh.shape[axis_name]

    def core(q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray) -> jnp.ndarray:
        L = q.shape[2]
        to = -(-L // n) * n
        if to != L:
            pad = ((0, 0), (0, 0), (0, to - L), (0, 0))
            q_p, k_p, v_p = (jnp.pad(t, pad) for t in (q, k, v))
        else:
            q_p, k_p, v_p = q, k, v
        if _CHECK_KW == "check_rep":
            # jax < 0.6 workaround: the legacy shard_map mis-partitions when
            # fused into surrounding computation in the same jit — inputs
            # arriving auto-sharded from upstream ops (conv -> ring) and
            # outputs consumed by a residual add both silently compute
            # garbage. Pinning both boundaries replicated sidesteps the bad
            # reshard; the new top-level shard_map partitions correctly
            # without either pin.
            rep = jax.sharding.NamedSharding(mesh, P())
            q_p, k_p, v_p = (
                lax.with_sharding_constraint(t, rep) for t in (q_p, k_p, v_p)
            )
        out = ring_attention_sharded(
            q_p, k_p, v_p, mesh, axis_name=axis_name,
            kv_len=None if to == L else L, head_axis=head_axis,
            block_size=block_size,
        )
        if _CHECK_KW == "check_rep":
            out = lax.with_sharding_constraint(
                out, jax.sharding.NamedSharding(mesh, P())
            )
        return out[:, :, :L]

    return core
