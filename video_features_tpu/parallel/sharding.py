"""Multi-chip sharding: mesh construction + GSPMD partition rules.

The reference has no inter-device communication at all — its only
parallelism is a static split of the video list across GPU threads (ref
main.py:49-55; SURVEY.md §2 parallelism table). The TPU-native framework
keeps that embarrassingly-parallel outer loop (parallel/scheduler.py) and
*adds* what the reference cannot do: sharded execution of one model call
across a ``jax.sharding.Mesh``, with XLA inserting the ICI collectives.

Axes:
- ``data``  — the frame/stack axis of one extraction batch. For video
  models this is also the *time* axis, so sharding it is the framework's
  sequence-parallel story: a long video's frame batch spreads over chips.
- ``model`` — tensor parallelism over attention heads / MLP hidden dim
  (Megatron-style column->row sharding, expressed purely as PartitionSpecs;
  the psum after the row-sharded matmul is inserted by GSPMD).

Multi-host: the same mesh built from ``jax.devices()`` after
``jax.distributed.initialize`` spans hosts; specs are unchanged (DCN for
dispatch, ICI for the collectives).
"""

from __future__ import annotations

from functools import partial
from typing import Optional, Sequence

import numpy as np

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def make_mesh(
    devices: Optional[Sequence] = None,
    data: Optional[int] = None,
    model: int = 1,
) -> Mesh:
    """A (data, model) mesh over ``devices`` (default: all of them)."""
    if devices is None:
        devices = jax.devices()
    n = len(devices)
    if data is None:
        if n % model != 0:
            raise ValueError(f"{n} devices not divisible by model={model}")
        data = n // model
    if data * model > n:
        raise ValueError(f"mesh {data}x{model} needs more than {n} devices")
    arr = np.asarray(devices[: data * model], dtype=object).reshape(data, model)
    return Mesh(arr, ("data", "model"))


def _path_names(path) -> list:
    return [p.key for p in path if hasattr(p, "key")]


def clip_vit_param_specs(params):
    """Megatron-style TP specs for models/clip/model.py's VisionTransformer.

    Column-parallel (shard output features over 'model'): q/k/v projections
    and the MLP up-projection ``c_fc``. Row-parallel (shard input features;
    GSPMD adds the psum): ``out_proj`` and the MLP down-projection
    ``c_proj``. Everything else (LayerNorms, embeddings, patchify conv,
    final proj) is replicated — it is tiny next to the block weights.
    """

    def spec(path, leaf):
        names = _path_names(path)
        parent = names[-2] if len(names) > 1 else ""
        last = names[-1] if names else ""
        if parent in ("q_proj", "k_proj", "v_proj", "c_fc"):
            return P(None, "model") if last == "kernel" else P("model")
        if parent in ("out_proj", "c_proj") and last == "kernel":
            return P("model", None)
        return P()

    return jax.tree_util.tree_map_with_path(spec, params)


def shard_params(params, mesh: Mesh, specs=None):
    """Place a param tree onto ``mesh`` under ``specs`` (default: CLIP TP)."""
    if specs is None:
        specs = clip_vit_param_specs(params)
    shardings = jax.tree.map(lambda s: NamedSharding(mesh, s), specs)
    return jax.device_put(params, shardings)


def multihost() -> bool:
    """True under a ``jax.distributed`` multi-controller runtime (a TPU
    pod slice, or the simulated 2-process cluster in test_multihost)."""
    return jax.process_count() > 1


def _mesh_out_sharding(mesh: Mesh, spec) -> NamedSharding:
    """THE decision point for pinned mesh output placement: the caller's
    spec single-host, REPLICATED (an all-gather at graph exit) under
    multi-host — ``np.asarray`` on a cross-host-sharded global array
    raises "not fully addressable" on every host, and features are tiny
    next to activations, so the gather is noise."""
    return NamedSharding(mesh, P() if multihost() else spec)


def build_sharded_apply(model, mesh: Mesh, batch_spec=P("data"),
                        out_spec=P("data")):
    """jit ``model.apply`` with the batch sharded over 'data'.

    Returns ``fn(params, x)``; params should already be placed with
    ``shard_params`` (their shardings flow into the jit as arguments).
    ``--mesh_context`` mode passes ``P()`` for both: the batch replicates
    and the token axis shards *inside* the model via ring attention.
    Output placement: ``_mesh_out_sharding``.
    """
    x_sharding = NamedSharding(mesh, batch_spec)
    out_sharding = _mesh_out_sharding(mesh, out_spec)

    @partial(jax.jit, out_shardings=out_sharding)
    def fn(p, x):
        x = jax.lax.with_sharding_constraint(x, x_sharding)
        return model.apply({"params": p}, x)

    return fn


# --- product-path helpers (--sharding mesh) ------------------------------
#
# Extractor ``_build(device)`` receives either a single jax.Device (queue
# mode, one executable per chip) or a Mesh (mesh mode, one GSPMD-sharded
# executable spanning every chip). These helpers let one _build body serve
# both without branching at every call site.


def is_mesh(device) -> bool:
    return isinstance(device, Mesh)


def place_params(params, device, specs_fn=None):
    """Put a host param tree on a device — or shard it over a mesh.

    ``specs_fn(params) -> spec tree`` supplies the mesh layout (e.g.
    ``clip_vit_param_specs`` for Megatron-style TP); None replicates every
    leaf (pure data parallelism — the right default for conv nets whose
    weights are small next to activations)."""
    if not is_mesh(device):
        return jax.device_put(params, device)
    if specs_fn is None:
        specs = jax.tree.map(lambda _: P(), params)
    else:
        specs = specs_fn(params)
    return shard_params(params, device, specs)


def pad_batch_for(device, batch: np.ndarray) -> np.ndarray:
    """Round axis 0 up so the mesh 'data' axis divides it (queue mode:
    no-op). Pad rows compute garbage that the caller slices off via its own
    row count — cheaper than uneven-sharding gymnastics."""
    n = batch.shape[0]
    if not is_mesh(device):
        return batch
    data = device.shape["data"]
    to = -(-n // data) * data
    if to != n:
        pad = [(0, to - n)] + [(0, 0)] * (batch.ndim - 1)
        batch = np.pad(batch, pad)
    return batch


def multihost_out_kwargs(device) -> dict:
    """``jax.jit`` kwargs pinning every output replicated on a mesh under
    a multi-controller runtime — extractors that jit with plain
    propagation (flow nets, i3d's per-shape fns) would otherwise fetch
    cross-host-sharded arrays, and ``np.asarray`` on one raises "not
    fully addressable" on every host. Single-host / non-mesh: {} (keep
    propagation: the flow nets' B-pair output axis is one short of the
    data-divisible frame axis, where an explicit 'data' sharding would be
    rejected)."""
    if is_mesh(device) and multihost():
        return {"out_shardings": NamedSharding(device, P())}
    return {}


def jit_sharded_forward(fn, device, n_out: int = 1):
    """jit ``fn(params, x)`` for either execution mode: plain jit on a
    single device; on a Mesh, pin each output per ``_mesh_out_sharding``
    ('data'-sharded single-host, replicated multi-host)."""
    if not is_mesh(device):
        return jax.jit(fn)
    out = _mesh_out_sharding(device, P("data"))
    return jax.jit(fn, out_shardings=out if n_out == 1 else (out,) * n_out)


def place_raw_payload(payload, device):
    """Transfer one ``--preprocess device`` payload — the
    ``(frames, (wt_y, idx_y), (wt_x, idx_x))`` triple from the host half.

    Queue mode: one plain ``device_put`` of the whole tuple. Mesh: the
    uint8 frame axis (axis 0 — already time-bucket padded by the
    extractor's ``prepare``, so the pad rows exist BEFORE the shard
    split) rounds up to 'data'-divisible, frames shard over 'data', and
    the per-resolution resample taps replicate — every shard resizes its
    own frame slice against the full tap tables (the taps are K x size,
    kilobytes next to the frames). The caller's row count slices the pad
    rows off at fetch, same as the host-preprocess mesh path.
    """
    if not is_mesh(device):
        return jax.device_put(payload, device)
    frames, wy, wx = payload
    frames = pad_batch_for(device, frames)
    batch = NamedSharding(device, P("data"))
    rep = NamedSharding(device, P())
    return jax.device_put((frames, wy, wx), (batch, (rep, rep), (rep, rep)))


def fused_payload_shardings(device):
    """The (data, rep) NamedSharding pair for a fused device-preprocess
    jit entry's payload roles: the raw frame/stack batch shards over
    'data'; the shape-contract metadata riding along (banded resample
    taps, crop offsets, padder grids) replicates — it is per-shape, not
    per-frame, and kilobytes next to the frames. graftcheck GC504
    resolves this helper by name, so declaring fused ``in_shardings``
    through it keeps the payload roles statically provable."""
    return NamedSharding(device, P("data")), NamedSharding(device, P())


def place_batch(x, device, spec=P("data")):
    """Transfer one input batch: device_put for a single device, sharded
    device_put over the mesh (axis 0 must already divide — see
    ``pad_batch_for``)."""
    if not is_mesh(device):
        return jax.device_put(x, device)
    return jax.device_put(x, NamedSharding(device, spec))
