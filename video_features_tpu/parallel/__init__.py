from video_features_tpu.parallel.devices import resolve_devices  # noqa: F401
from video_features_tpu.parallel.scheduler import parallel_feature_extraction  # noqa: F401
