"""Device addressing: ``--device_ids`` transparently indexes ``jax.devices()``.

The reference smuggles the device through a scattered index tensor's
``.device`` attribute (ref main.py:43-53). Here devices are first-class
``jax.Device`` objects: extractors place inputs with ``jax.device_put`` and
jit-compile once per device (the XLA analog of the reference's build-the-
model-inside-forward-per-replica pattern, ref
models/resnet/extract_resnet.py:52-71).
"""

from __future__ import annotations

from typing import List, Optional, Sequence


def resolve_devices(cfg=None, *, cpu: Optional[bool] = None,
                    device_ids: Optional[Sequence[int]] = None) -> List["jax.Device"]:
    import jax

    if cfg is not None:
        cpu = cfg.cpu if cpu is None else cpu
        device_ids = cfg.device_ids if device_ids is None else device_ids
    if cpu:
        return [jax.local_devices(backend="cpu")[0]]
    devices = list(jax.devices())
    if device_ids:
        bad = [i for i in device_ids if i < 0 or i >= len(devices)]
        if bad:
            raise ValueError(
                f"device_ids {bad} out of range: only {len(devices)} devices "
                f"visible ({[str(d) for d in devices]})"
            )
        return [devices[i] for i in device_ids]
    return devices
