"""Device addressing: ``--device_ids`` transparently indexes ``jax.devices()``.

The reference smuggles the device through a scattered index tensor's
``.device`` attribute (ref main.py:43-53). Here devices are first-class
``jax.Device`` objects: extractors place inputs with ``jax.device_put`` and
jit-compile once per device (the XLA analog of the reference's build-the-
model-inside-forward-per-replica pattern, ref
models/resnet/extract_resnet.py:52-71).
"""

from __future__ import annotations

import os
from typing import List, Optional, Sequence


def pin_platform(platform: Optional[str] = None) -> None:
    """Re-assert the jax platform through the config API (None = from the
    JAX_PLATFORMS env var; no-op if neither is set).

    TPU plugins (axon) register a backend-discovery hook that ignores the
    JAX_PLATFORMS env var captured at interpreter startup and dials the
    chip tunnel — which can block for minutes. Pinning via the config API
    skips discovery entirely; harmless if backends are already up. Every
    entry point must call this before touching jax devices.
    """
    import jax

    platform = platform or os.environ.get("JAX_PLATFORMS")
    if platform:
        try:
            jax.config.update("jax_platforms", platform)
        except Exception:
            pass


def resolve_devices(cfg=None, *, cpu: Optional[bool] = None,
                    device_ids: Optional[Sequence[int]] = None) -> List["jax.Device"]:
    import jax

    if cfg is not None:
        cpu = cfg.cpu if cpu is None else cpu
        device_ids = cfg.device_ids if device_ids is None else device_ids
    # --cpu wins over the env: a --cpu run must never touch the TPU runtime.
    pin_platform("cpu" if cpu else None)
    if cpu:
        return [jax.local_devices(backend="cpu")[0]]
    devices = list(jax.devices())
    sharding = getattr(cfg, "sharding", "queue") if cfg is not None else "queue"
    if sharding != "mesh" and jax.process_count() > 1:
        # queue-mode multi-process runs are embarrassingly parallel: each
        # process drives only its OWN chips (the reference's per-machine
        # contract, ref main.py:43-48), so --device_ids index into this
        # process's LOCAL devices. Mesh mode keeps the global view — its
        # dispatches are collective across all processes.
        devices = list(jax.local_devices())
    if device_ids:
        bad = [i for i in device_ids if i < 0 or i >= len(devices)]
        if bad:
            raise ValueError(
                f"device_ids {bad} out of range: only {len(devices)} devices "
                f"visible ({[str(d) for d in devices]})"
            )
        return [devices[i] for i in device_ids]
    return devices
