"""Video-level data parallelism: a dynamic work queue over devices.

The reference's only parallelism strategy is a static even split of the
video list across GPU threads via ``replicate``/``scatter``/
``parallel_apply`` (ref main.py:49-55). The TPU-native redesign keeps the
"video list is the dataset" contract but replaces the static split with a
shared work queue drained by one host thread per device: decode (the usual
bottleneck) load-balances across chips instead of leaving chips idle
behind a long shard, and a dead worker's remaining items are picked up by
the others instead of being silently lost (the reference failure mode
noted in SURVEY.md §5).

Threads, not processes: cv2 decode and XLA dispatch both release the GIL,
and each device runs its own jit-compiled executable.
"""

from __future__ import annotations

import queue
import threading
import traceback
from typing import List, Optional, Sequence


def parallel_feature_extraction(extractor, devices: Optional[Sequence] = None) -> None:
    """Extract features for every video in ``extractor.path_list``.

    Each device thread repeatedly pulls one video index and runs the
    extractor on it; per-video error isolation lives inside the extractor
    (ref models/CLIP/extract_clip.py:69-87).
    """
    from video_features_tpu.parallel.devices import resolve_devices

    if devices is None:
        devices = resolve_devices(extractor.config)

    n = len(extractor.path_list)
    work: "queue.Queue[int]" = queue.Queue()
    for idx in range(n):
        work.put(idx)

    errors: List[BaseException] = []

    def worker(device) -> None:
        # Build (and compile) this device's model once, up front.
        try:
            extractor.warmup(device)
        except Exception as e:  # noqa: BLE001 - surface below
            errors.append(e)
            traceback.print_exc()
            return
        while True:
            try:
                idx = work.get_nowait()
            except queue.Empty:
                return
            try:
                extractor([idx], device=device)
            except KeyboardInterrupt:
                errors.append(KeyboardInterrupt())
                return
            finally:
                work.task_done()

    if len(devices) == 1:
        worker(devices[0])
    else:
        threads = [
            threading.Thread(target=worker, args=(d,), daemon=True, name=f"extract-{d}")
            for d in devices
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()

    extractor.progress.close()
    if errors and all(isinstance(e, KeyboardInterrupt) for e in errors):
        raise KeyboardInterrupt
    if len(errors) == len(devices) and devices:
        # every worker died before draining the queue -> nothing ran; raise
        raise RuntimeError(f"all {len(devices)} extraction workers failed") from errors[0]
