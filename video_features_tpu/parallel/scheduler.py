"""Video-level data parallelism: a dynamic work queue over devices.

The reference's only parallelism strategy is a static even split of the
video list across GPU threads via ``replicate``/``scatter``/
``parallel_apply`` (ref main.py:49-55). The TPU-native redesign keeps the
"video list is the dataset" contract but replaces the static split with a
shared work queue drained by one host thread per device: decode (the usual
bottleneck) load-balances across chips instead of leaving chips idle
behind a long shard, and a dead worker's items — including the one it was
holding when it died — are re-queued and picked up by the surviving
workers instead of being silently lost (the reference failure mode noted
in SURVEY.md §5).

Threads, not processes: cv2 decode and XLA dispatch both release the GIL,
and each device runs its own jit-compiled executable.
"""

from __future__ import annotations

import queue
import threading
import traceback
from typing import Dict, List, Optional, Sequence, Tuple

from video_features_tpu.runtime.faults import NULL_MANIFEST


def mesh_feature_extraction(extractor, devices: Optional[Sequence] = None) -> None:
    """``--sharding mesh``: one GSPMD-sharded executable over every
    selected device instead of one replica per device.

    Builds a (data, model) ``jax.sharding.Mesh`` (``--mesh_model`` sets the
    tensor-parallel axis; the frame/stack batch shards over 'data') and
    runs the ordinary extraction loop with the mesh as the extractor's
    "device" — the same ``build_sharded_apply`` path the driver's
    ``dryrun_multichip`` validates. The decode pipeline (--decode_workers)
    still overlaps host work with the sharded compute.
    """
    from video_features_tpu.parallel.devices import resolve_devices
    from video_features_tpu.parallel.sharding import make_mesh

    if devices is None:
        devices = resolve_devices(extractor.config)
    try:
        if not getattr(extractor, "mesh_capable", False):
            raise ValueError(
                f"--sharding mesh is not supported for feature_type "
                f"{extractor.feature_type!r}: {type(extractor).__name__} does "
                "not declare mesh support (mesh_capable); use --sharding queue"
            )
        model_axis = int(extractor.config.mesh_model or 1)
        if model_axis > 1 and not getattr(extractor, "mesh_tp_capable", False):
            # DP-only models replicate params: chips along 'model' would
            # redo identical work while looking busy. Refuse loudly.
            raise ValueError(
                f"--mesh_model {model_axis} needs tensor-parallel param "
                f"specs, which {type(extractor).__name__} does not define "
                "(only the batch axis shards); use --mesh_model 1"
            )
        if getattr(extractor.config, "mesh_context", False) and not getattr(
            extractor, "mesh_context_capable", False
        ):
            raise ValueError(
                f"--mesh_context needs a transformer token axis to shard; "
                f"{type(extractor).__name__} does not declare support "
                "(mesh_context_capable)"
            )
        mesh = make_mesh(devices, model=model_axis)
        extractor(device=mesh)
    finally:
        extractor.progress.close()


def parallel_feature_extraction(extractor, devices: Optional[Sequence] = None) -> None:
    """Extract features for every video in ``extractor.path_list``.

    Each device thread repeatedly pulls one video index and runs the
    extractor on it; per-video error isolation lives inside the extractor
    (ref models/CLIP/extract_clip.py:69-87). A worker that dies OUTSIDE
    that isolation (warmup failure, sink/OOM escape) re-queues its
    in-flight item and is retired; remaining items are drained in another
    pass over the still-live devices, so the run either produces every
    output or raises.
    """
    from video_features_tpu.parallel.devices import resolve_devices

    if devices is None:
        devices = resolve_devices(extractor.config)

    n = len(extractor.path_list)
    own = range(n)
    # Multi-host queue runs are embarrassingly parallel, the reference's
    # across-GPU contract lifted across hosts: each process drives only
    # its ADDRESSABLE devices, owns a disjoint strided slice of the video
    # list, and sinks its own outputs (extract/base.py::_sink_or_collect
    # gates the process-0-only sink on mesh mode for this reason). No
    # collectives are issued anywhere in this path (advisor r4).
    import jax

    if jax.process_count() > 1:
        pidx = jax.process_index()
        local = [d for d in devices if d.process_index == pidx]
        if local:
            devices = local
        own = range(pidx, n, jax.process_count())
        # the bar was sized for the whole list at construction; this
        # process only ever advances it len(own) times
        extractor.progress.total = len(own)
        extractor.progress.refresh()
    work: "queue.Queue[int]" = queue.Queue()
    for idx in own:
        work.put(idx)

    # Every worker death lands in the run manifest (the extractor may be
    # a test fake without one — the NULL manifest swallows records).
    manifest = getattr(extractor, "manifest", None) or NULL_MANIFEST
    errors: List[Tuple[object, BaseException]] = []  # (device, exc)
    # How many times each index was re-queued by a worker death: capped
    # at the config retry budget, after which the video is recorded
    # failed instead of ping-ponging between dying workers forever.
    requeue_counts: Dict[int, int] = {}
    requeue_lock = threading.Lock()
    retries = int(getattr(extractor.config, "retries", 2) or 0)
    dead: set = set()
    interrupted = threading.Event()

    def record_death(device, exc: BaseException, phase: str) -> None:
        errors.append((device, exc))
        dead.add(device)
        traceback.print_exc()
        manifest.event(
            "worker_death",
            device=str(device),
            phase=phase,
            error_type=type(exc).__name__,
            message=str(exc)[:300],
        )

    def requeue_or_drop(chunk: List[int]) -> None:
        for idx in chunk:
            with requeue_lock:
                requeue_counts[idx] = count = requeue_counts.get(idx, 0) + 1
            if count > retries:
                entry = extractor.path_list[idx]
                video = getattr(extractor, "_video_key", lambda e: str(e))(entry)
                print(
                    f"Dropping {video}: re-queued {count - 1} time(s) by "
                    "worker deaths, retry budget exhausted"
                )
                manifest.record(
                    video,
                    "failed",
                    stage="worker",
                    error_class="transient",
                    message=f"worker died {count} times holding this video",
                    attempts=count,
                )
                extractor.progress.update()
            else:
                work.put(idx)

    # Workers pull CHUNKS so the extractor's async host pipeline
    # (--decode_workers prefetch, extract/base.py::_run_pipelined) has a
    # window of upcoming videos to decode ahead; chunk=1 would starve it.
    # Chunks stay modest so the shared queue still load-balances across
    # devices; a single device just takes everything in one call. With
    # --video_batch aggregation the chunk must cover at least two full
    # groups, or every chunk boundary would flush a padded partial group.
    workers_per_device = int(getattr(extractor.config, "decode_workers", 0) or 0)
    video_batch = int(getattr(extractor.config, "video_batch", 1) or 1)
    chunk_size = (
        n
        if len(devices) == 1
        else max(1, 2 * (workers_per_device + 1), 2 * video_batch)
    )

    def worker(device) -> None:
        # Build (and compile) this device's model once, up front.
        try:
            extractor.warmup(device)
        except Exception as e:  # noqa: BLE001 - surface below
            record_death(device, e, "warmup")
            return
        while not interrupted.is_set():
            chunk: List[int] = []
            try:
                for _ in range(chunk_size):
                    chunk.append(work.get_nowait())
            except queue.Empty:
                pass
            if not chunk:
                return
            try:
                extractor(chunk, device=device)
            except KeyboardInterrupt:
                interrupted.set()
                return
            except BaseException as e:  # noqa: BLE001 - worker death
                # An escape past the extractor's per-video isolation kills
                # this worker. Put the in-flight chunk back for the next
                # drain pass (otherwise it would be silently lost — capped
                # per index so repeatedly-fatal videos are recorded failed
                # rather than ping-ponged between dying workers) and record
                # the death so the run can't exit clean with missing
                # outputs. Items of the chunk that already completed may
                # re-run — harmless, the sink's atomic writes are
                # idempotent.
                record_death(device, e, "extract")
                requeue_or_drop(chunk)
                return

    live = list(devices)
    while live and not work.empty() and not interrupted.is_set():
        if len(live) == 1:
            worker(live[0])
        else:
            threads = [
                threading.Thread(
                    target=worker, args=(d,), daemon=True, name=f"extract-{d}"
                )
                for d in live
            ]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
        live = [d for d in live if d not in dead]

    extractor.progress.close()
    if interrupted.is_set():
        raise KeyboardInterrupt
    if not work.empty():
        # every device's worker died with items still queued — outputs ARE
        # missing; a clean exit here would hide that (VERDICT r1 weak #4).
        # Summarize EVERY death (the old message chained only errors[0],
        # discarding the rest — ISSUE 3 satellite).
        deaths = "; ".join(
            f"{d}: {type(e).__name__}: {str(e)[:200]}" for d, e in errors
        )
        raise RuntimeError(
            f"all extraction workers died with {work.qsize()} of {len(own)} videos "
            f"unprocessed ({len(errors)} worker death(s): {deaths})"
        ) from (errors[0][1] if errors else None)
    if errors:
        # queue drained (survivors re-ran the re-queued items) but some
        # worker(s) died along the way — say so instead of exiting silently
        deaths = "; ".join(
            f"{d}: {type(e).__name__}: {str(e)[:200]}" for d, e in errors
        )
        print(
            f"WARNING: {len(errors)} extraction worker(s) died mid-run; "
            "their videos were re-queued and completed by surviving workers "
            f"(or recorded failed past the retry cap). Deaths: {deaths}"
        )
